"""Task drivers: the boundary that actually runs workloads.

Semantic parity with /root/reference/plugins/drivers/driver.go:51
(DriverPlugin: Fingerprint/StartTask/WaitTask/StopTask/InspectTask) and the
shipped drivers: the scriptable mock driver (drivers/mock/driver.go:117,152
-- run_for / exit_code / start_error / start_block_for / kill_after), and
raw_exec / exec fork-exec drivers (drivers/rawexec, drivers/exec,
drivers/shared/executor). In-process classes instead of go-plugin gRPC
subprocesses: the subprocess *workload* boundary is real (fork/exec), the
*plugin* boundary collapses to a registry -- the reference needs process
isolation because drivers are third-party binaries; here they are part of
the framework. The reattach contract (recover a live task by handle after
agent restart) is preserved, which is what client state restore needs.
"""
from __future__ import annotations

import os
import signal
import subprocess
import tarfile
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..structs import Task
from .taskenv import interpolate

TASK_STATE_PENDING = "pending"
TASK_STATE_RUNNING = "running"
TASK_STATE_DEAD = "dead"


def parse_duration(val) -> float:
    if val is None:
        return 0.0
    if isinstance(val, (int, float)):
        return float(val)
    s = str(val).strip()
    try:
        if s.endswith("ms"):
            return float(s[:-2]) / 1000.0
        if s.endswith("s"):
            return float(s[:-1])
        if s.endswith("m"):
            return float(s[:-1]) * 60.0
        return float(s)
    except ValueError:
        return 0.0


@dataclass
class TaskHandle:
    """Opaque recoverable handle (reference: drivers.TaskHandle)."""

    task_id: str = ""
    driver: str = ""
    pid: int = 0
    started_at: float = 0.0
    driver_state: Dict[str, object] = field(default_factory=dict)


@dataclass
class ExitResult:
    exit_code: int = 0
    signal: int = 0
    err: str = ""
    oom_killed: bool = False

    def successful(self) -> bool:
        return self.exit_code == 0 and self.signal == 0 and not self.err


class DriverError(Exception):
    pass


def _run_captured(argv: List[str], env: Dict[str, str],
                  cwd: Optional[str], timeout: float) -> Dict[str, object]:
    """Shared one-shot exec: captured output + DriverError translation."""
    try:
        proc = subprocess.run(argv, cwd=cwd, env=dict(env),
                              capture_output=True, timeout=timeout)
    except FileNotFoundError as e:
        raise DriverError(str(e)) from e
    except subprocess.TimeoutExpired as e:
        raise DriverError(f"exec timed out after {timeout}s") from e
    return {"stdout": proc.stdout.decode("utf-8", "replace"),
            "stderr": proc.stderr.decode("utf-8", "replace"),
            "exit_code": proc.returncode}


class Driver:
    """(reference: plugins/drivers/driver.go DriverPlugin)"""

    name = "base"

    def fingerprint(self) -> Dict[str, object]:
        """-> {detected, healthy, attributes}"""
        return {"detected": True, "healthy": True, "attributes": {}}

    def start_task(self, task_id: str, task: Task, env: Dict[str, str],
                   task_dir) -> TaskHandle:
        raise NotImplementedError

    def wait_task(self, handle: TaskHandle,
                  timeout: Optional[float] = None) -> Optional[ExitResult]:
        """Block until exit (or timeout); None on timeout."""
        raise NotImplementedError

    def stop_task(self, handle: TaskHandle, kill_timeout: float = 5.0) -> None:
        raise NotImplementedError

    def inspect_task(self, handle: TaskHandle) -> str:
        """-> task state string"""
        raise NotImplementedError

    def recover_task(self, handle: TaskHandle) -> bool:
        """Re-attach after agent restart; False if unrecoverable."""
        return False

    def exec_task(self, handle: TaskHandle, env: Dict[str, str],
                  task_dir, cmd: List[str],
                  timeout: float = 10.0) -> Dict[str, object]:
        """One-shot command in the task's context (reference:
        plugins/drivers ExecTask; the interactive streaming form is
        `nomad alloc exec`). Base semantics: run in the task dir with
        the task env -- isolated drivers override to enter the task's
        namespaces."""
        cwd = getattr(task_dir, "local_dir", None) if task_dir else None
        return _run_captured(list(cmd), env, cwd, timeout)

    def signal_task(self, handle: TaskHandle, sig: str) -> None:
        """Deliver a signal to the task's process (reference:
        plugins/drivers SignalTask). Process-backed drivers signal the
        handle pid; others raise."""
        if handle.pid <= 0:
            raise DriverError(
                f"driver {self.name!r} does not support signals")
        signum = getattr(signal, sig if sig.startswith("SIG")
                         else f"SIG{sig}", None)
        if signum is None:
            raise DriverError(f"unknown signal {sig!r}")
        try:
            os.kill(handle.pid, int(signum))
        except ProcessLookupError as e:
            raise DriverError("task process is gone") from e


# ---------------------------------------------------------------------------
class _MockInstance:
    __slots__ = ("started_at", "run_for", "exit_code", "kill_after",
                 "stopped", "exited", "exit_result")

    def __init__(self, run_for: float, exit_code: int, kill_after: float):
        self.started_at = time.time()
        self.run_for = run_for
        self.exit_code = exit_code
        self.kill_after = kill_after
        self.stopped = threading.Event()
        self.exited = threading.Event()
        self.exit_result: Optional[ExitResult] = None


class MockDriver(Driver):
    """Scriptable fake (reference: drivers/mock/driver.go:117 Config:
    start_error, start_block_for, run_for, exit_code, exit_err_msg,
    kill_after). The backbone of client/scheduler tests."""

    name = "mock"

    def __init__(self):
        self._instances: Dict[str, _MockInstance] = {}
        self._lock = threading.Lock()

    def start_task(self, task_id: str, task: Task, env: Dict[str, str],
                   task_dir) -> TaskHandle:
        cfg = task.config or {}
        if cfg.get("start_error"):
            raise DriverError(str(cfg["start_error"]))
        block = parse_duration(cfg.get("start_block_for"))
        if block > 0:
            time.sleep(min(block, 5.0))
        inst = _MockInstance(
            run_for=parse_duration(cfg.get("run_for")),
            exit_code=int(cfg.get("exit_code", 0) or 0),
            kill_after=parse_duration(cfg.get("kill_after")))
        # scripted output lands in the task's log files (reference:
        # drivers/mock stdout_string/stdout_repeat)
        if task_dir is not None and cfg.get("stdout_string"):
            repeat = int(cfg.get("stdout_repeat", 1) or 1)
            with open(task_dir.stdout_path(), "ab") as f:
                f.write((str(cfg["stdout_string"]) * repeat).encode())
        with self._lock:
            self._instances[task_id] = inst
        timer = threading.Thread(target=self._run, args=(task_id, inst),
                                 daemon=True, name=f"mock-task-{task_id[:8]}")
        timer.start()
        return TaskHandle(task_id=task_id, driver=self.name,
                          started_at=inst.started_at,
                          driver_state={"run_for": inst.run_for,
                                        "exit_code": inst.exit_code})

    def _run(self, task_id: str, inst: _MockInstance) -> None:
        if inst.run_for > 0:
            inst.stopped.wait(inst.run_for)
        else:
            # run forever until stopped; bounded re-check (nomadlint
            # join-with-timeout) keeps the parked task diagnosable
            while not inst.stopped.wait(60.0):
                pass
        if inst.exit_result is None:
            if inst.stopped.is_set():
                inst.exit_result = ExitResult(exit_code=0,
                                              signal=int(signal.SIGTERM))
            else:
                inst.exit_result = ExitResult(exit_code=inst.exit_code)
        inst.exited.set()

    def wait_task(self, handle: TaskHandle,
                  timeout: Optional[float] = None) -> Optional[ExitResult]:
        inst = self._instances.get(handle.task_id)
        if inst is None:
            return ExitResult(err="unknown task")
        if not inst.exited.wait(timeout):
            return None
        return inst.exit_result

    def stop_task(self, handle: TaskHandle, kill_timeout: float = 5.0) -> None:
        inst = self._instances.get(handle.task_id)
        if inst is not None:
            # kill_after: the task lingers after the kill signal
            # (reference: mock driver Config.KillAfter), bounded by the
            # caller's kill_timeout like a real unresponsive process
            if inst.kill_after > 0:
                time.sleep(min(inst.kill_after, kill_timeout))
            inst.stopped.set()
            inst.exited.wait(kill_timeout)

    def inspect_task(self, handle: TaskHandle) -> str:
        inst = self._instances.get(handle.task_id)
        if inst is None or inst.exited.is_set():
            return TASK_STATE_DEAD
        return TASK_STATE_RUNNING

    def recover_task(self, handle: TaskHandle) -> bool:
        """Mock tasks are in-process: a restart means re-running the clock
        from the handle's recorded script."""
        if handle.task_id in self._instances:
            return True
        run_for = float(handle.driver_state.get("run_for", 0.0))
        elapsed = time.time() - handle.started_at
        remaining = max(run_for - elapsed, 0.01) if run_for > 0 else 0.0
        inst = _MockInstance(
            run_for=remaining,
            exit_code=int(handle.driver_state.get("exit_code", 0)),
            kill_after=0.0)
        with self._lock:
            self._instances[handle.task_id] = inst
        threading.Thread(target=self._run, args=(handle.task_id, inst),
                         daemon=True).start()
        return True


# ---------------------------------------------------------------------------
class RawExecDriver(Driver):
    """Fork/exec without isolation (reference: drivers/rawexec). Config:
    command, args. Stdout/stderr stream to the alloc log dir."""

    name = "raw_exec"

    def __init__(self):
        self._procs: Dict[str, subprocess.Popen] = {}
        self._results: Dict[str, ExitResult] = {}
        self._lock = threading.Lock()

    def start_task(self, task_id: str, task: Task, env: Dict[str, str],
                   task_dir) -> TaskHandle:
        cfg = task.config or {}
        command = str(cfg.get("command", ""))
        if not command:
            raise DriverError("raw_exec requires config.command")
        args = [interpolate(str(a), None, None, env)
                for a in cfg.get("args", [])]
        argv = [command] + args
        # bridge-mode allocs: enter the alloc's network namespace
        # (reference: the CNI netns the docker/exec drivers join;
        # redesign: client/netns.py)
        netns = (getattr(task_dir.alloc, "netns", None)
                 if task_dir is not None else None)
        if netns:
            argv = ["ip", "netns", "exec", netns] + argv
        stdout = open(task_dir.stdout_path(), "ab") if task_dir else None
        stderr = open(task_dir.stderr_path(), "ab") if task_dir else None
        try:
            proc = subprocess.Popen(
                argv,
                env={**os.environ, **env},
                cwd=task_dir.local_dir if task_dir else None,
                stdout=stdout or subprocess.DEVNULL,
                stderr=stderr or subprocess.DEVNULL,
                start_new_session=True)      # own process group for kill
        except OSError as e:
            raise DriverError(f"failed to start {command}: {e}") from e
        finally:
            for fh in (stdout, stderr):
                if fh is not None:
                    fh.close()
        with self._lock:
            self._procs[task_id] = proc
        return TaskHandle(task_id=task_id, driver=self.name, pid=proc.pid,
                          started_at=time.time())

    def wait_task(self, handle: TaskHandle,
                  timeout: Optional[float] = None) -> Optional[ExitResult]:
        proc = self._procs.get(handle.task_id)
        if proc is None:
            return self._results.get(handle.task_id,
                                     ExitResult(err="unknown task"))
        try:
            code = proc.wait(timeout)
        except subprocess.TimeoutExpired:
            return None
        result = (ExitResult(exit_code=code) if code >= 0
                  else ExitResult(signal=-code))
        with self._lock:
            self._results[handle.task_id] = result
        return result

    def stop_task(self, handle: TaskHandle, kill_timeout: float = 5.0) -> None:
        proc = self._procs.get(handle.task_id)
        if proc is None or proc.poll() is not None:
            return
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            proc.wait(kill_timeout)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            proc.wait(5.0)

    def inspect_task(self, handle: TaskHandle) -> str:
        proc = self._procs.get(handle.task_id)
        if proc is None:
            # recovered handle: probe the pid
            if handle.pid and _pid_alive(handle.pid):
                return TASK_STATE_RUNNING
            return TASK_STATE_DEAD
        return (TASK_STATE_DEAD if proc.poll() is not None
                else TASK_STATE_RUNNING)

    def recover_task(self, handle: TaskHandle) -> bool:
        """Re-attach by pid (reference: executor reattach via
        plugins/shared -- the driver handle stores the plugin's pid)."""
        return bool(handle.pid) and _pid_alive(handle.pid)


class ExecDriver(RawExecDriver):
    """Isolated fork/exec (reference: drivers/exec via libcontainer,
    executor_linux.go:35). With root + namespaces available the payload
    runs chrooted into the task dir (read-only binds of the host
    toolchain, the reference's allocdir chroot file map) inside fresh
    mount+PID namespaces with cpu/memory cgroup limits
    (client/executor.py). Without privileges it degrades to raw_exec
    semantics under the same driver contract, exactly like the
    reference's non-Linux executor."""

    name = "exec"

    def __init__(self):
        super().__init__()
        self._cgroups: Dict[str, object] = {}

    def fingerprint(self) -> Dict[str, object]:
        from .executor import probe_caps
        caps = probe_caps()
        return {"detected": True, "healthy": True,
                "attributes": {"driver.exec.isolation":
                               "chroot+ns+cgroup" if caps.namespaces
                               else "none"}}

    def start_task(self, task_id: str, task: Task, env: Dict[str, str],
                   task_dir) -> TaskHandle:
        from .executor import probe_caps
        caps = probe_caps()
        if not caps.namespaces or task_dir is None:
            return super().start_task(task_id, task, env, task_dir)
        cfg = task.config or {}
        command = str(cfg.get("command", ""))
        if not command:
            raise DriverError("exec requires config.command")
        args = [interpolate(str(a), None, None, env)
                for a in cfg.get("args", [])]
        # the shared alloc dir lives outside the task dir -> bind it in,
        # plus any volume mounts the hooks resolved onto the task dir
        from .executor import DEFAULT_CHROOT_BINDS
        binds = list(DEFAULT_CHROOT_BINDS)
        binds.append(f"{task_dir.alloc.shared_dir}:/alloc")
        binds.extend(getattr(task_dir, "extra_binds", []) or [])
        return self._start_isolated(
            task_id, [command] + args, env, task_dir,
            root=task_dir.dir, workdir="/local",
            cpu_shares=task.resources.cpu,
            memory_mb=task.resources.memory_mb, binds=binds)

    def _start_isolated(self, task_id, argv, env, task_dir, root, workdir,
                        cpu_shares, memory_mb, binds) -> TaskHandle:
        from .executor import launch_isolated
        # sandbox env vars must name CHROOT paths, not host paths
        env = dict(env)
        env.update({"NOMAD_TASK_DIR": "/local",
                    "NOMAD_ALLOC_DIR": "/alloc",
                    "NOMAD_SECRETS_DIR": "/secrets"})
        try:
            proc, cgroup = launch_isolated(
                task_id, argv, env, root=root,
                launcher_dir=task_dir.tmp_dir,
                stdout_path=task_dir.stdout_path(),
                stderr_path=task_dir.stderr_path(),
                cpu_shares=cpu_shares, memory_mb=memory_mb,
                binds=binds, workdir=workdir,
                netns=getattr(task_dir.alloc, "netns", None))
        except OSError as e:
            raise DriverError(f"failed to start isolated task: {e}") from e
        state: Dict[str, object] = {"isolated": True}
        with self._lock:
            self._procs[task_id] = proc
            if cgroup is not None:
                self._cgroups[task_id] = cgroup
                state["cgroup_version"] = cgroup.version
                state["cgroup_paths"] = list(cgroup.paths)
        return TaskHandle(task_id=task_id, driver=self.name, pid=proc.pid,
                          started_at=time.time(), driver_state=state)

    def exec_task(self, handle: TaskHandle, env: Dict[str, str],
                  task_dir, cmd: List[str],
                  timeout: float = 10.0) -> Dict[str, object]:
        """Enter the live task's namespaces + chroot via nsenter when the
        task runs isolated (reference: executor Exec entering the
        container); degrades to the base in-task-dir semantics
        otherwise."""
        if not handle.driver_state.get("isolated") or handle.pid <= 0:
            return super().exec_task(handle, env, task_dir, cmd,
                                     timeout=timeout)

        def sandboxed(pid: int) -> bool:
            try:
                host = os.stat("/")
                root = os.stat(f"/proc/{pid}/root/.")
                return (root.st_dev, root.st_ino) != (host.st_dev,
                                                      host.st_ino)
            except OSError:
                return False

        def payload_pid(pid: int) -> Optional[int]:
            # handle.pid is the LAUNCHER; descend the child chain and
            # stop at the FIRST process whose root is the sandbox
            # (deeper descendants may be short-lived grandchildren)
            for _ in range(6):
                if sandboxed(pid):
                    return pid
                try:
                    with open(f"/proc/{pid}/task/{pid}/children") as fh:
                        kids = fh.read().split()
                except OSError:
                    return None
                if not kids:
                    return None
                pid = int(kids[0])
            return None

        # the launcher chroots the payload asynchronously after start:
        # wait briefly for a sandboxed descendant, and NEVER run against
        # an unsandboxed target (that would execute on the host root)
        target = payload_pid(handle.pid)
        deadline = time.time() + 5.0
        while target is None and time.time() < deadline:
            time.sleep(0.05)
            target = payload_pid(handle.pid)
        if target is None:
            raise DriverError("task sandbox not available for exec")
        # sandbox paths, like _start_isolated rewrites for the payload
        env = dict(env)
        env.update({"NOMAD_TASK_DIR": "/local",
                    "NOMAD_ALLOC_DIR": "/alloc",
                    "NOMAD_SECRETS_DIR": "/secrets"})
        # in-sandbox `timeout` kills the command itself: subprocess.run's
        # timeout only kills nsenter, orphaning the forked child inside
        # the task's pid namespace
        # -n joins the task's network namespace too: for bridge-mode
        # allocs an exec'd probe must see the ports the task bound
        # inside its netns, not the host's
        full = (["nsenter", "-t", str(target), "-m", "-p", "-n", "-r",
                 "-w", "--", "timeout", f"{timeout:.1f}"] + list(cmd))
        return _run_captured(full, env, None, timeout + 2.0)

    def wait_task(self, handle: TaskHandle,
                  timeout: Optional[float] = None) -> Optional[ExitResult]:
        result = super().wait_task(handle, timeout)
        if result is not None:
            self._cleanup_cgroup(handle.task_id)
        return result

    def stop_task(self, handle: TaskHandle, kill_timeout: float = 5.0) -> None:
        if not handle.driver_state.get("isolated"):
            return super().stop_task(handle, kill_timeout)
        # Graceful stop must reach the PAYLOAD, not the unshare
        # supervisor: SIGTERM to the supervisor kills it and --kill-child
        # SIGKILLs the payload instantly, zeroing the kill_timeout grace
        # window. The cgroup lists exactly the payload tree (the
        # supervisor never joins it).
        proc = self._procs.get(handle.task_id)
        cgroup = self._cgroups.get(handle.task_id)
        delivered = False
        if cgroup is not None:
            for pid in cgroup.procs():
                try:
                    os.kill(pid, signal.SIGTERM)
                    delivered = True
                except (ProcessLookupError, PermissionError):
                    pass
        if delivered and proc is not None:
            try:
                proc.wait(kill_timeout)
            except subprocess.TimeoutExpired:
                pass
        super().stop_task(handle, kill_timeout if not delivered else 1.0)
        self._cleanup_cgroup(handle.task_id)

    def _cleanup_cgroup(self, task_id: str) -> None:
        cgroup = self._cgroups.pop(task_id, None)
        if cgroup is not None:
            cgroup.kill()       # reap any escaped descendants
            cgroup.destroy()

    def recover_task(self, handle: TaskHandle) -> bool:
        """Re-attach after agent restart; rebuild the cgroup handle from
        driver_state so exit-time cleanup still happens."""
        ok = super().recover_task(handle)
        paths = handle.driver_state.get("cgroup_paths")
        if paths:
            from .cgroups import Cgroup
            cgroup = Cgroup(int(handle.driver_state.get(
                "cgroup_version", 1)), list(paths))
            if ok:
                with self._lock:
                    self._cgroups[handle.task_id] = cgroup
            else:
                cgroup.kill()
                cgroup.destroy()
        return ok

    def task_cgroup(self, task_id: str):
        """The live Cgroup for a task (stats + tests)."""
        return self._cgroups.get(task_id)


class ContainerDriver(ExecDriver):
    """Minimal container driver (reference: drivers/docker, scoped to the
    oci-rootfs essentials): config.image names a rootfs directory or a
    .tar/.tar.gz unpacked into the task sandbox; the payload chroots into
    that rootfs inside mount+PID namespaces with NO host binds -- only the
    task's /local, /alloc and /secrets sandbox dirs and a fresh /proc are
    mounted in, with cpu/memory cgroup limits applied."""

    name = "container"

    def fingerprint(self) -> Dict[str, object]:
        from .executor import probe_caps
        caps = probe_caps()
        return {"detected": caps.namespaces, "healthy": caps.namespaces,
                "attributes": {"driver.container.rootfs": "chroot"}}

    def start_task(self, task_id: str, task: Task, env: Dict[str, str],
                   task_dir) -> TaskHandle:
        from .executor import probe_caps
        if not probe_caps().namespaces:
            raise DriverError("container driver requires namespace support")
        if task_dir is None:
            raise DriverError("container driver requires a task dir")
        cfg = task.config or {}
        image = str(cfg.get("image", ""))
        if not image:
            raise DriverError("container requires config.image")
        rootfs, img_cfg = self._materialize_rootfs(image, task_dir)
        command = str(cfg.get("command", ""))
        args = [interpolate(str(a), None, None, env)
                for a in cfg.get("args", [])]
        argv = img_cfg.argv(command, args)
        if not argv:
            raise DriverError(
                "container has no command: set config.command or use an "
                "image with an Entrypoint/Cmd")
        # image env underlays the task env (docker semantics)
        merged_env = dict(env)
        for kv in img_cfg.env:
            k, _, v = kv.partition("=")
            merged_env.setdefault(k, v)
        binds = [] if not cfg.get("host_binds") \
            else [str(b) for b in cfg["host_binds"]]
        # sandbox dirs appear at the nomad-standard mount points
        for sub, target in ((task_dir.local_dir, "/local"),
                            (task_dir.secrets_dir, "/secrets"),
                            (task_dir.alloc.shared_dir, "/alloc")):
            binds.append(f"{sub}:{target}")
        binds.extend(getattr(task_dir, "extra_binds", []) or [])
        workdir = (str(cfg.get("work_dir", ""))
                   or img_cfg.working_dir or "/")
        return self._start_isolated(
            task_id, argv, merged_env, task_dir,
            root=rootfs, workdir=workdir,
            cpu_shares=task.resources.cpu,
            memory_mb=task.resources.memory_mb, binds=binds)

    @staticmethod
    def _materialize_rootfs(image: str, task_dir):
        """Flatten the image (OCI layout / docker-archive / plain rootfs
        dir or tar, client/oci.py) into the task sandbox so container
        writes never mutate the shared artifact (reference: docker's
        per-container layer). Returns (rootfs path, ImageConfig)."""
        import json as _json

        from . import oci

        rootfs = os.path.join(task_dir.dir, "rootfs")
        cfg_path = os.path.join(task_dir.dir, "rootfs.config.json")
        if os.path.isdir(rootfs):
            # restart: reuse the materialized fs + its recorded config
            img_cfg = oci.ImageConfig()
            try:
                img_cfg = oci.ImageConfig(**_json.load(open(cfg_path)))
            except (OSError, ValueError, TypeError):
                pass
            return rootfs, img_cfg
        # materialize into a scratch dir and rename into place so a crash
        # mid-copy can never leave a half-built rootfs that a restart
        # would silently trust
        partial = rootfs + ".partial"
        import shutil
        shutil.rmtree(partial, ignore_errors=True)
        try:
            img_cfg = oci.materialize(image, partial, task_dir.tmp_dir)
        except oci.ImageError as e:
            raise DriverError(str(e)) from e
        except (OSError, ValueError, tarfile.TarError) as e:
            # corrupt/truncated artifacts must FAIL the task, not kill
            # the runner thread (it catches DriverError only)
            raise DriverError(f"bad container image {image!r}: {e}") from e
        with open(cfg_path, "w") as f:
            _json.dump({"env": img_cfg.env,
                        "entrypoint": img_cfg.entrypoint,
                        "cmd": img_cfg.cmd,
                        "working_dir": img_cfg.working_dir}, f)
        os.rename(partial, rootfs)
        return rootfs, img_cfg


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


class JavaDriver(ExecDriver):
    """Run a jar/class under the JVM with exec isolation (reference:
    drivers/java -- argv assembly around the shared executor). Config:
    jar_path | class, args, jvm_args."""

    name = "java"

    def fingerprint(self) -> Dict[str, object]:
        import shutil as _sh
        java = _sh.which("java")
        return {"detected": java is not None, "healthy": java is not None,
                "attributes": ({"driver.java.runtime": java}
                               if java else {})}

    def start_task(self, task_id: str, task: Task, env: Dict[str, str],
                   task_dir) -> TaskHandle:
        cfg = dict(task.config or {})
        jvm_args = [str(a) for a in cfg.get("jvm_args", [])]
        args = [str(a) for a in cfg.get("args", [])]
        if cfg.get("jar_path"):
            argv = ["java", *jvm_args, "-jar", str(cfg["jar_path"]), *args]
        elif cfg.get("class"):
            argv = ["java", *jvm_args, str(cfg["class"]), *args]
        else:
            raise DriverError("java requires config.jar_path or "
                              "config.class")
        shim = Task(name=task.name, driver=self.name,
                    config={"command": argv[0], "args": argv[1:]},
                    resources=task.resources)
        return super().start_task(task_id, shim, env, task_dir)


def _find_qemu():
    import shutil as _sh
    return _sh.which("qemu-system-x86_64") or _sh.which("qemu-kvm")


class QemuDriver(RawExecDriver):
    """Boot a VM image under qemu (reference: drivers/qemu). Config:
    image_path, format (optional; qemu probes when unset), accelerator,
    memory derived from resources, extra args via config.args."""

    name = "qemu"

    def fingerprint(self) -> Dict[str, object]:
        qemu = _find_qemu()
        return {"detected": qemu is not None, "healthy": qemu is not None,
                "attributes": ({"driver.qemu.binary": qemu}
                               if qemu else {})}

    def start_task(self, task_id: str, task: Task, env: Dict[str, str],
                   task_dir) -> TaskHandle:
        qemu = _find_qemu()
        if qemu is None:
            raise DriverError("qemu binary not present on this host")
        cfg = dict(task.config or {})
        image = str(cfg.get("image_path", ""))
        if not image:
            raise DriverError("qemu requires config.image_path")
        drive = f"file={image}"
        if cfg.get("format"):
            drive += f",format={cfg['format']}"
        argv = [qemu, "-nographic",
                "-m", f"{max(task.resources.memory_mb, 32)}M",
                "-drive", drive]
        if cfg.get("accelerator"):
            argv += ["-accel", str(cfg["accelerator"])]
        argv += [str(a) for a in cfg.get("args", [])]
        shim = Task(name=task.name, driver=self.name,
                    config={"command": argv[0], "args": argv[1:]},
                    resources=task.resources)
        return super().start_task(task_id, shim, env, task_dir)


# ---------------------------------------------------------------------------
class DriverRegistry:
    """Per-client driver instances (reference: client/pluginmanager/
    drivermanager -- instance lifecycle + fingerprint aggregation)."""

    def __init__(self, enabled: Optional[List[str]] = None,
                 external: Optional[List[List[str]]] = None):
        all_drivers = {d.name: d for d in
                       (MockDriver(), RawExecDriver(), ExecDriver(),
                        ContainerDriver(), JavaDriver(), QemuDriver())}
        if enabled is not None:
            all_drivers = {k: v for k, v in all_drivers.items()
                           if k in enabled}
        # out-of-process plugins (reference: plugins/base go-plugin
        # subprocesses); a plugin that fails its handshake is skipped --
        # never fatal to the client, but always diagnosed
        for argv in external or []:
            try:
                from ..plugins.driver import ExternalDriver
                drv = ExternalDriver(argv)
                all_drivers[drv.name] = drv
            except Exception as e:  # noqa: BLE001
                import sys
                print(f"[nomad-tpu] external driver plugin {argv!r} "
                      f"failed to start: {e}", file=sys.stderr)
        self._drivers = all_drivers

    def shutdown(self) -> None:
        """Stop plugin subprocesses (in-process drivers have no-op
        shutdowns)."""
        for d in self._drivers.values():
            stop = getattr(d, "shutdown", None)
            if stop is not None:
                stop()

    def get(self, name: str) -> Driver:
        d = self._drivers.get(name)
        if d is None:
            raise DriverError(f"driver {name!r} not found")
        return d

    def fingerprints(self) -> Dict[str, Dict[str, object]]:
        return {name: d.fingerprint() for name, d in self._drivers.items()}
