"""Task environment: NOMAD_* variables + ${...} interpolation.

Semantic parity with /root/reference/client/taskenv/ (env.go Builder --
NOMAD_ALLOC_*, NOMAD_TASK_*, NOMAD_CPU_LIMIT..., node attr/meta
interpolation ${node.*} ${attr.*} ${meta.*} ${env.*}, port variables
NOMAD_PORT_<label> / NOMAD_ADDR_<label>).
"""
from __future__ import annotations

import re
from typing import Dict, Optional

from ..structs import Allocation, Node, Task

_VAR_RE = re.compile(r"\$\{([^}]+)\}")


def build_env(alloc: Allocation, task: Task, node: Optional[Node],
              task_dir: Optional[object] = None) -> Dict[str, str]:
    """(reference: taskenv/env.go Builder.Build)"""
    env: Dict[str, str] = {}
    env["NOMAD_ALLOC_ID"] = alloc.id
    env["NOMAD_ALLOC_NAME"] = alloc.name
    env["NOMAD_ALLOC_INDEX"] = str(_alloc_index(alloc.name))
    env["NOMAD_GROUP_NAME"] = alloc.task_group
    env["NOMAD_TASK_NAME"] = task.name
    env["NOMAD_JOB_ID"] = alloc.job_id
    env["NOMAD_JOB_NAME"] = alloc.job.name if alloc.job else alloc.job_id
    env["NOMAD_NAMESPACE"] = alloc.namespace
    env["NOMAD_DC"] = node.datacenter if node else ""
    env["NOMAD_REGION"] = "global"
    if task_dir is not None:
        env["NOMAD_ALLOC_DIR"] = task_dir.alloc.shared_dir
        env["NOMAD_TASK_DIR"] = task_dir.local_dir
        env["NOMAD_SECRETS_DIR"] = task_dir.secrets_dir
        # bridge-mode allocs (client/netns.py): the task sees its netns
        # address and the bridge gateway (the route back to the host)
        alloc_ip = getattr(task_dir.alloc, "alloc_ip", None)
        if alloc_ip:
            env["NOMAD_ALLOC_IP"] = alloc_ip
            env["NOMAD_HOST_GATEWAY"] = getattr(
                task_dir.alloc, "gateway_ip", "")
    if task.resources is not None:
        env["NOMAD_CPU_LIMIT"] = str(task.resources.cpu)
        env["NOMAD_MEMORY_LIMIT"] = str(task.resources.memory_mb)
    # allocated ports (reference: env.go addPorts)
    tr = (alloc.allocated_resources.tasks.get(task.name)
          if alloc.allocated_resources else None)
    if tr is not None:
        for net in tr.networks:
            for p in list(net.reserved_ports) + list(net.dynamic_ports):
                label = p.label.upper().replace("-", "_")
                env[f"NOMAD_PORT_{label}"] = str(p.value)
                env[f"NOMAD_IP_{label}"] = net.ip
                env[f"NOMAD_ADDR_{label}"] = f"{net.ip}:{p.value}"
    if alloc.allocated_resources is not None:
        alloc_ip = env.get("NOMAD_ALLOC_IP", "")
        for pm in alloc.allocated_resources.shared.ports:
            label = pm.label.upper().replace("-", "_")
            env[f"NOMAD_HOST_PORT_{label}"] = str(pm.value)
            if alloc_ip:
                # bridge mode (reference: env.go setPortMapEnvs): the
                # task binds the MAPPED port inside its namespace; the
                # host port lives on the forwarder
                to = pm.to or pm.value
                env[f"NOMAD_PORT_{label}"] = str(to)
                env[f"NOMAD_IP_{label}"] = alloc_ip
                env[f"NOMAD_ADDR_{label}"] = f"{alloc_ip}:{to}"
            else:
                env[f"NOMAD_PORT_{label}"] = str(pm.value)
                env[f"NOMAD_IP_{label}"] = pm.host_ip
                env[f"NOMAD_ADDR_{label}"] = f"{pm.host_ip}:{pm.value}"
    # user-specified env wins, after interpolation
    for k, v in (task.env or {}).items():
        env[k] = interpolate(str(v), alloc, node, env)
    # inside a netns, loopback no longer reaches the host: rewrite the
    # connect sidecar's server address onto the bridge gateway
    gw = env.get("NOMAD_HOST_GATEWAY", "")
    if gw and "NOMAD_CONNECT_HTTP_ADDR" in env:
        env["NOMAD_CONNECT_HTTP_ADDR"] = (
            env["NOMAD_CONNECT_HTTP_ADDR"]
            .replace("//127.0.0.1", f"//{gw}")
            .replace("//localhost", f"//{gw}"))
    return env


def interpolate(s: str, alloc: Optional[Allocation], node: Optional[Node],
                env: Optional[Dict[str, str]] = None) -> str:
    """Replace ${node.*}, ${attr.*}, ${meta.*}, ${env.*}, ${NOMAD_*}
    (reference: taskenv ReplaceEnv + client interpolation in drivers)."""

    def repl(m: re.Match) -> str:
        key = m.group(1).strip()
        if node is not None:
            if key == "node.unique.id":
                return node.id
            if key == "node.unique.name":
                return node.name
            if key == "node.datacenter":
                return node.datacenter
            if key == "node.class":
                return node.node_class
            if key == "node.pool":
                return node.node_pool
            if key == "node.region":
                return "global"
            if key.startswith("attr."):
                return node.attributes.get(key[len("attr."):], "")
            if key.startswith("meta."):
                return node.meta.get(key[len("meta."):], "")
        if key.startswith("env.") and env is not None:
            return env.get(key[len("env."):], "")
        if env is not None and key in env:
            return env[key]
        return m.group(0)        # leave unknown vars untouched

    return _VAR_RE.sub(repl, s)


def _alloc_index(name: str) -> int:
    """job.group[3] -> 3 (reference: structs.AllocName index extraction)."""
    try:
        return int(name.rsplit("[", 1)[1].rstrip("]"))
    except (IndexError, ValueError):
        return 0
