"""Node fingerprinting: populate Node.attributes + NodeResources.

Semantic parity with /root/reference/client/fingerprint_manager.go and
client/fingerprint/ (one fingerprinter per concern: arch, cpu, memory,
storage, network, host, nomad version, env_*). TPU-first addition: an
accelerator fingerprinter that surfaces jax-visible TPU/device topology as
node attributes and a device resource group, the way the reference's
env_aws/gce probes surface cloud metadata and device plugins surface GPUs
(reference: client/fingerprint/env_gce.go, plugins/device/).
"""
from __future__ import annotations

import os
import platform
import shutil
import socket
import time
from typing import Dict, List, Optional, Tuple

from ..structs import (
    Node, NodeCpuResources, NodeDeviceResource, NodeDiskResources,
    NodeMemoryResources, NodeResources, NetworkResource, generate_uuid,
)

VERSION = "0.1.0"


class Fingerprinter:
    """One concern's probe. Returns (attributes, mutate_fn|None)."""

    name = "base"

    def fingerprint(self, node: Node) -> Dict[str, str]:
        raise NotImplementedError


class ArchFingerprinter(Fingerprinter):
    name = "arch"

    def fingerprint(self, node: Node) -> Dict[str, str]:
        return {"cpu.arch": platform.machine()}


class OSFingerprinter(Fingerprinter):
    name = "os"

    def fingerprint(self, node: Node) -> Dict[str, str]:
        return {"os.name": platform.system().lower(),
                "os.version": platform.release(),
                "kernel.name": platform.system().lower(),
                "kernel.version": platform.release()}


class HostFingerprinter(Fingerprinter):
    name = "host"

    def fingerprint(self, node: Node) -> Dict[str, str]:
        return {"unique.hostname": socket.gethostname()}


class CpuFingerprinter(Fingerprinter):
    name = "cpu"

    def fingerprint(self, node: Node) -> Dict[str, str]:
        from . import numalib
        topo = numalib.scan()
        cores = topo.core_count or os.cpu_count() or 1
        mhz = self._base_mhz()
        total = int(cores * mhz)
        node.node_resources.cpu = NodeCpuResources(
            cpu_shares=total, total_core_count=cores,
            reservable_cores=topo.all_cores() or list(range(cores)))
        return {"cpu.numcores": str(cores),
                "cpu.frequency": str(int(mhz)),
                "cpu.totalcompute": str(total),
                "numa.node_count": str(topo.node_count)}

    @staticmethod
    def _base_mhz() -> float:
        try:
            with open("/proc/cpuinfo", encoding="utf-8") as fh:
                for line in fh:
                    if line.lower().startswith("cpu mhz"):
                        return float(line.split(":", 1)[1])
        except (OSError, ValueError):
            pass
        return 1000.0


class MemoryFingerprinter(Fingerprinter):
    name = "memory"

    def fingerprint(self, node: Node) -> Dict[str, str]:
        total_mb = self._total_mb()
        node.node_resources.memory = NodeMemoryResources(
            memory_mb=total_mb)
        return {"memory.totalbytes": str(total_mb << 20)}

    @staticmethod
    def _total_mb() -> int:
        try:
            with open("/proc/meminfo", encoding="utf-8") as fh:
                for line in fh:
                    if line.startswith("MemTotal:"):
                        return int(line.split()[1]) >> 10
        except (OSError, ValueError, IndexError):
            pass
        return 1024


class StorageFingerprinter(Fingerprinter):
    name = "storage"

    def __init__(self, data_dir: str = "/tmp"):
        self.data_dir = data_dir

    def fingerprint(self, node: Node) -> Dict[str, str]:
        try:
            usage = shutil.disk_usage(self.data_dir)
            free_mb = usage.free >> 20
            total_mb = usage.total >> 20
        except OSError:
            free_mb = total_mb = 10240
        node.node_resources.disk = NodeDiskResources(disk_mb=free_mb)
        return {"unique.storage.volume": self.data_dir,
                "unique.storage.bytestotal": str(total_mb << 20),
                "unique.storage.bytesfree": str(free_mb << 20)}


class NetworkFingerprinter(Fingerprinter):
    name = "network"

    def fingerprint(self, node: Node) -> Dict[str, str]:
        ip = "127.0.0.1"
        try:
            # UDP connect learns the outbound interface address; no traffic
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.connect(("10.255.255.255", 1))
            ip = s.getsockname()[0]
            s.close()
        except OSError:
            pass
        if not node.node_resources.networks:
            node.node_resources.networks = [
                NetworkResource(mode="host", device="eth0", ip=ip,
                                mbits=1000)]
        return {"unique.network.ip-address": ip}


class NomadFingerprinter(Fingerprinter):
    name = "nomad"

    def fingerprint(self, node: Node) -> Dict[str, str]:
        return {"nomad.version": VERSION,
                "nomad.revision": "tpu-native"}


class AcceleratorFingerprinter(Fingerprinter):
    """Surfaces jax-visible accelerators as node attributes + a device
    group, so jobs can constrain on `${attr.tpu.count}` or request
    `device "tpu"` (the reference's device-plugin fingerprint path,
    plugins/device/). Probing jax is optional and lazy: client agents on
    CPU-only hosts skip it."""

    name = "accelerator"

    def __init__(self, probe_jax: bool = False):
        self.probe_jax = probe_jax

    def fingerprint(self, node: Node) -> Dict[str, str]:
        if not self.probe_jax:
            return {}
        try:
            import jax
            devices = jax.devices()
        except Exception:       # noqa: BLE001 - no accelerator runtime
            return {}
        kinds: Dict[str, List] = {}
        for d in devices:
            kinds.setdefault(getattr(d, "device_kind", d.platform), []) \
                .append(d)
        attrs = {"tpu.count": str(sum(len(v) for k, v in kinds.items()
                                      if "tpu" in k.lower()))}
        for kind, devs in kinds.items():
            vendor = "google" if "tpu" in kind.lower() else devs[0].platform
            node.node_resources.devices.append(NodeDeviceResource(
                vendor=vendor, type="tpu" if "tpu" in kind.lower()
                else devs[0].platform,
                name=kind, instance_ids=[str(d.id) for d in devs]))
            attrs[f"accelerator.{kind}.count"] = str(len(devs))
        return attrs


DEFAULT_FINGERPRINTERS = (
    ArchFingerprinter, OSFingerprinter, HostFingerprinter, CpuFingerprinter,
    MemoryFingerprinter, StorageFingerprinter, NetworkFingerprinter,
    NomadFingerprinter,
)


class FingerprintManager:
    """Runs every fingerprinter against a Node
    (reference: client/fingerprint_manager.go setupFingerprinters)."""

    def __init__(self, data_dir: str = "/tmp", probe_jax: bool = False,
                 extra: Optional[List[Fingerprinter]] = None):
        self.fingerprinters: List[Fingerprinter] = [
            cls(data_dir) if cls is StorageFingerprinter else cls()
            for cls in DEFAULT_FINGERPRINTERS]
        self.fingerprinters.append(AcceleratorFingerprinter(probe_jax))
        self.fingerprinters.extend(extra or [])

    def fingerprint_node(self, node: Optional[Node] = None,
                         name: str = "", datacenter: str = "dc1",
                         node_class: str = "") -> Node:
        if node is None:
            node = Node(id=generate_uuid(), name=name or socket.gethostname(),
                        datacenter=datacenter, node_class=node_class,
                        node_resources=NodeResources())
        applied = []
        for fp in self.fingerprinters:
            try:
                attrs = fp.fingerprint(node)
            except Exception:   # noqa: BLE001 - a probe must not kill boot
                continue
            node.attributes.update(attrs)
            applied.append(fp.name)
        node.attributes["fingerprinters"] = ",".join(applied)
        node.compute_class()
        return node
