"""Node agent (reference: /root/reference/client/)."""
from .agent import SimClient  # noqa: F401
