"""Node agent (reference: /root/reference/client/)."""
from .agent import SimClient  # noqa: F401
from .alloc_runner import AllocRunner  # noqa: F401
from .allocdir import AllocDir, TaskDir  # noqa: F401
from .client import Client, LocalServerConn, ServerConn  # noqa: F401
from .drivers import (  # noqa: F401
    Driver, DriverRegistry, ExecDriver, MockDriver, RawExecDriver,
    TaskHandle,
)
from .fingerprint import FingerprintManager  # noqa: F401
from .state_db import StateDB  # noqa: F401
from .task_runner import TaskRunner, TaskState  # noqa: F401
