"""Client agent: registration, heartbeats, alloc watch loop, restore, GC.

Semantic parity with /root/reference/client/client.go (NewClient :350,
registerAndHeartbeat :1734, watchAllocations :2280 -- blocking
Node.GetClientAllocs pull, runAllocs :2538 -- diff desired vs running,
restoreState :1215 -- re-attach via driver handles, heartbeatstop.go --
stop_after_client_disconnect). The server boundary is the `ServerConn`
interface: in-process for the dev topology, HTTP for real deployments --
the client is pull-based either way, which is what makes 10K-node fleets
tractable (no server->client push).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..structs import (
    Allocation, Node,
    ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED, ALLOC_DESIRED_RUN,
)
from .alloc_runner import AllocRunner
from .drivers import DriverRegistry
from .fingerprint import FingerprintManager
from .state_db import StateDB


class ServerConn:
    """Client->server RPC surface (reference: client/rpc.go +
    servers manager client/servers/)."""

    def register_node(self, node: Node) -> None:
        raise NotImplementedError

    def heartbeat(self, node_id: str) -> float:
        raise NotImplementedError

    def pull_allocs(self, node_id: str, min_index: int,
                    timeout: float) -> tuple:
        """Blocking pull -> (allocs, index)
        (reference: Node.GetClientAllocs node_endpoint.go:1170)."""
        raise NotImplementedError

    def update_allocs(self, updates: List[Allocation]) -> None:
        raise NotImplementedError

    def get_alloc(self, alloc_id: str) -> Optional[Allocation]:
        raise NotImplementedError

    def register_services(self, regs) -> None:
        """(reference: ServiceRegistration.Upsert RPC)"""
        raise NotImplementedError

    def sign_identity(self, claims: dict) -> Optional[str]:
        """Mint a workload identity JWT (reference: the server-side
        signing the identity hook relies on). None = unsupported."""
        return None

    def workload_variable(self, jwt: str, path: str):
        """Fetch a decrypted Variable with a workload identity
        (reference analog: DeriveVaultToken -> native Variables)."""
        raise NotImplementedError

    def csi_volume(self, namespace: str, vol_id: str):
        """-> CSIVolume or None (volume hook attach path)."""
        raise NotImplementedError


class LocalServerConn(ServerConn):
    """In-process server (dev agent topology)."""

    def __init__(self, server):
        self.server = server

    def register_node(self, node: Node) -> None:
        self.server.register_node(node)

    def heartbeat(self, node_id: str) -> float:
        return self.server.heartbeat(node_id)

    def pull_allocs(self, node_id: str, min_index: int,
                    timeout: float) -> tuple:
        index = self.server.state.block_until(min_index, timeout=timeout,
                                              tables=("allocs",))
        return self.server.state.allocs_by_node(node_id), index

    def update_allocs(self, updates: List[Allocation]) -> None:
        self.server.update_allocs_from_client(updates)

    def get_alloc(self, alloc_id: str) -> Optional[Allocation]:
        return self.server.state.alloc_by_id(alloc_id)

    def register_services(self, regs) -> None:
        self.server.upsert_services(regs)

    def sign_identity(self, claims: dict) -> Optional[str]:
        return self.server.sign_workload_identity(claims)

    def workload_variable(self, jwt: str, path: str):
        return self.server.workload_variable(jwt, path)

    def csi_volume(self, namespace: str, vol_id: str):
        return self.server.state.csi_volume_by_id(namespace, vol_id)


MAX_TERMINAL_RUNNERS = 50     # client GC watermark (reference: client/gc.go)


class Client:
    """(reference: client/client.go Client)"""

    def __init__(self, conn: ServerConn, data_dir: str,
                 node: Optional[Node] = None, name: str = "",
                 drivers: Optional[DriverRegistry] = None,
                 probe_jax: bool = False, identity_signer=None,
                 device_plugins=None, csi_plugins=None,
                 api_addr: str = "", serve_http: bool = False):
        self.conn = conn
        self.data_dir = data_dir
        # bridge networking (client/netns.py): the AllocRunner invokes
        # this factory only for bridge-mode groups, so host-network-only
        # clients never pay the netns capability probe
        self._network_manager = None
        self._network_lock = threading.Lock()
        self.drivers = drivers or DriverRegistry()
        # device plugins feed node devices (reference: devicemanager)
        self.device_manager = None
        if device_plugins:
            from ..plugins.device import DeviceManager
            self.device_manager = DeviceManager(device_plugins)
        # CSI plugins: per-plugin-id subprocesses; the node advertises
        # healthy node plugins for scheduler feasibility
        # (reference: client/pluginmanager/csimanager)
        self.csi_manager = None
        if csi_plugins:
            from ..plugins.csi import CSIManager
            self.csi_manager = CSIManager(data_dir, csi_plugins)
        self.state_db = StateDB(data_dir)
        if identity_signer is None:
            def identity_signer(claims, _c=conn):
                return _c.sign_identity(claims)
        self.identity_signer = identity_signer
        self.secrets_fetcher = conn.workload_variable
        fm = FingerprintManager(data_dir=data_dir, probe_jax=probe_jax)
        self.node = fm.fingerprint_node(node=node, name=name)
        if api_addr:
            # lets workloads reach the HTTP API via ${attr.nomad.api_addr}
            # (the connect sidecar's catalog resolution needs it)
            self.node.attributes["nomad.api_addr"] = api_addr
        # server->client forwarding channel (reference: client/rpc.go):
        # the node advertises its own listener so ANY server agent can
        # proxy fs/logs/stats for allocs it does not host in-process
        self.http = None
        if serve_http:
            from .http import ClientHttpServer
            self.http = ClientHttpServer(self)
            self.node.attributes["nomad.client_http"] = self.http.address
        # driver fingerprints -> node.drivers (reference: drivermanager)
        from ..structs import DriverInfo
        for dname, fp in self.drivers.fingerprints().items():
            self.node.drivers[dname] = DriverInfo(
                detected=bool(fp.get("detected")),
                healthy=bool(fp.get("healthy")))
        if self.device_manager is not None:
            self.node.node_resources.devices.extend(
                self.device_manager.all_devices())
        self._probe_csi_health()
        self.node.compute_class()
        # restore node identity across restarts
        prev = self.state_db.node_id()
        if prev:
            self.node.id = prev
        else:
            self.state_db.put_node_id(self.node.id)

        self.runners: Dict[str, AllocRunner] = {}
        self._services_registered: set = set()
        self._runner_lock = threading.Lock()
        self._last_index = 0
        self._last_ok_heartbeat = time.time()
        self._shutdown = threading.Event()
        self._frozen = threading.Event()    # fault injection: partition
        self._threads: List[threading.Thread] = []
        self.heartbeat_ttl = 10.0

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        self.restore()
        if self.http is not None:
            self.http.start()
        self.conn.register_node(self.node)
        loops = [(self._heartbeat_loop, "heartbeat"),
                 (self._watch_allocations, "alloc-watch"),
                 (self._health_loop, "health"),
                 (self._heartbeatstop_loop, "heartbeatstop")]
        if self.csi_manager is not None:
            loops.append((self._csi_fingerprint_loop, "csi-fingerprint"))
        for fn, label in loops:
            t = threading.Thread(target=fn, daemon=True,
                                 name=f"client-{label}-{self.node.name}")
            t.start()
            self._threads.append(t)

    def _get_network_manager(self):
        from .netns import bridge_caps, shared_manager
        with self._network_lock:
            if self._network_manager is None and bridge_caps():
                # process-global: the bridge subnet is host-global state
                self._network_manager = shared_manager()
            return self._network_manager

    def shutdown(self) -> None:
        self._shutdown.set()
        if self.http is not None:
            self.http.shutdown()
        with self._runner_lock:
            runners = list(self.runners.values())
        for r in runners:
            r.stop(timeout=2.0)
        # plugin subprocesses must not outlive the client
        if self.device_manager is not None:
            self.device_manager.shutdown()
        if self._network_manager is not None:
            with self._runner_lock:
                ids = list(self.runners)
            for alloc_id in ids:
                try:
                    self._network_manager.destroy(alloc_id)
                except Exception:   # noqa: BLE001 -- best-effort
                    pass
        if self.csi_manager is not None:
            self.csi_manager.shutdown()
        self.drivers.shutdown()

    # -- fault injection (parity with SimClient for tests) -------------
    def freeze(self) -> None:
        self._frozen.set()

    def thaw(self) -> None:
        self._frozen.clear()

    # -- restore (reference: client.go:1215 restoreState) --------------
    def restore(self) -> None:
        for alloc_id in self.state_db.alloc_ids():
            alloc = self.conn.get_alloc(alloc_id)
            if alloc is None or alloc.terminal_status():
                self.state_db.delete_alloc(alloc_id)
                continue
            tasks = self.state_db.get_alloc_tasks(alloc_id)
            runner = AllocRunner(
                alloc, self.drivers, self.data_dir, node=self.node,
                on_update=self._on_runner_update,
                identity_signer=self.identity_signer,
                secrets_fetcher=self.secrets_fetcher,
                device_manager=self.device_manager,
                csi_manager=self.csi_manager,
                csi_volume_info=self.conn.csi_volume,
                network_manager=self._get_network_manager)
            with self._runner_lock:
                self.runners[alloc_id] = runner
            states = {name: st for name, (st, _h) in tasks.items()}
            handles = {name: h for name, (_st, h) in tasks.items()}
            runner.restore(states, handles)

    # -- heartbeats (reference: registerAndHeartbeat :1734) ------------
    def _probe_csi_health(self) -> bool:
        """Probe every CSI plugin's own readiness into
        node.csi_node_plugins; returns True when any health flag changed.
        Health comes from the plugin's probe, not blind optimism: an
        unready plugin must not attract placements -- and a plugin that
        becomes ready later must not leave the node ineligible forever,
        so the heartbeat loop re-probes (reference: csimanager's
        periodic fingerprint loop)."""
        if self.csi_manager is None:
            return False
        changed = False
        for pid in self.csi_manager.plugin_ids():
            try:
                ready = bool(self.csi_manager.plugins[pid]
                             .probe().get("ready", False))
            except Exception:  # noqa: BLE001 -- plugin failure
                ready = False
            prev = self.node.csi_node_plugins.get(pid, {}).get("healthy")
            if prev != ready:
                changed = True
            self.node.csi_node_plugins[pid] = {"healthy": ready}
        return changed

    def _csi_fingerprint_loop(self) -> None:
        """Periodic plugin health re-probe on its OWN thread (reference:
        csimanager's fingerprint loop): plugin RPCs are blocking pipe
        calls, and a wedged plugin subprocess must never stall the
        heartbeat thread into a server-side node-down sweep."""
        while not self._shutdown.is_set():
            if self._shutdown.wait(5.0):
                return
            if self._frozen.is_set():
                continue
            try:
                if self._probe_csi_health():
                    # changed plugin health must reach the scheduler's
                    # feasibility view
                    self.conn.register_node(self.node)
            except Exception:  # noqa: BLE001 -- server unreachable
                pass

    def _heartbeat_loop(self) -> None:
        while not self._shutdown.is_set():
            interval = max(self.heartbeat_ttl / 3.0, 0.05)
            if self._shutdown.wait(interval):
                return
            if self._frozen.is_set():
                continue
            try:
                ttl = self.conn.heartbeat(self.node.id)
                if ttl:
                    self.heartbeat_ttl = ttl
                    now = time.time()
                    if now - self._last_ok_heartbeat > self.heartbeat_ttl:
                        # we likely missed our TTL: the server may have
                        # swept our services on node-down -- re-register
                        self._services_registered.clear()
                    self._last_ok_heartbeat = now
                    self._reconcile_services()
                else:
                    # server doesn't know us (restart/state loss):
                    # re-register (reference: client retryRegisterNode on
                    # heartbeat 'node not found'); the server's node-down
                    # sweep dropped our services, so re-register them too
                    self.conn.register_node(self.node)
                    self._services_registered.clear()
            except Exception:   # noqa: BLE001 - server unreachable
                pass

    def _reconcile_services(self) -> None:
        """Register services for running allocs not yet in the catalog
        (idempotent ids; covers recovery after a node-down sweep)."""
        from .serviceregistration import build_registrations
        with self._runner_lock:
            runners = [r for r in self.runners.values()
                       if r.client_status == "running"
                       and r.alloc.id not in self._services_registered]
        for r in runners:
            regs = build_registrations(r.alloc, self.node)
            self._services_registered.add(r.alloc.id)
            if regs:
                try:
                    self.conn.register_services(regs)
                except Exception:   # noqa: BLE001
                    self._services_registered.discard(r.alloc.id)

    # -- fs + logs API (reference: client/fs_endpoint.go List/Stat/
    #    ReadAt + logs; served on the client, reached via agent HTTP) ---
    def _alloc_root(self, alloc_id: str) -> str:
        import os
        with self._runner_lock:
            runner = self.runners.get(alloc_id)
        if runner is None:
            raise KeyError(f"alloc {alloc_id} not found on this node")
        return os.path.normpath(runner.alloc_dir.alloc_dir)

    def _safe_path(self, alloc_id: str, rel: str) -> str:
        """Resolve rel against the alloc dir, refusing escapes -- both
        lexical (..) and via symlinks inside the alloc dir
        (reference: fs_endpoint.go path sandboxing)."""
        import os
        root = os.path.realpath(self._alloc_root(alloc_id))
        full = os.path.realpath(os.path.join(root, rel.lstrip("/")))
        if not (full == root or full.startswith(root + os.sep)):
            raise PermissionError(f"path escapes alloc dir: {rel}")
        return full

    def fs_list(self, alloc_id: str, path: str = "/") -> List[dict]:
        import os
        full = self._safe_path(alloc_id, path)
        out = []
        for name in sorted(os.listdir(full)):
            p = os.path.join(full, name)
            # lstat: a dangling symlink must not break the whole listing
            st = os.lstat(p)
            out.append({"name": name, "is_dir": os.path.isdir(p),
                        "size": st.st_size, "mod_time": st.st_mtime})
        return out

    def fs_stat(self, alloc_id: str, path: str) -> dict:
        import os
        full = self._safe_path(alloc_id, path)
        st = os.stat(full)
        return {"name": os.path.basename(full),
                "is_dir": os.path.isdir(full),
                "size": st.st_size, "mod_time": st.st_mtime}

    def fs_logs_total(self, alloc_id: str, task: str,
                      log_type: str = "stdout") -> int:
        """Total bytes across a task's rotated log frames -- the
        follow stream's cursor base."""
        import os
        if log_type not in ("stdout", "stderr"):
            raise ValueError(f"invalid log type {log_type!r}")
        log_dir = self._safe_path(alloc_id, "alloc/logs")
        return sum(os.path.getsize(os.path.join(log_dir, f))
                   for f in os.listdir(log_dir)
                   if f.startswith(f"{task}.{log_type}."))

    def fs_read(self, alloc_id: str, path: str, offset: int = 0,
                limit: int = 1 << 20) -> bytes:
        """A NEGATIVE offset tails the file (last |offset| bytes)."""
        import os as _os
        with open(self._safe_path(alloc_id, path), "rb") as f:
            if offset < 0:
                size = _os.fstat(f.fileno()).st_size
                offset = max(0, size + offset)
            f.seek(max(0, offset))
            return f.read(max(0, min(limit, 1 << 24)))

    def alloc_stats(self, alloc_id: str) -> dict:
        """Live per-task resource usage (reference: client
        allocations.Stats endpoint): cgroup stats for isolated tasks,
        /proc RSS for plain ones."""
        with self._runner_lock:
            runner = self.runners.get(alloc_id)
        if runner is None:
            raise KeyError(f"alloc {alloc_id} not running here")
        # the runner thread may still be inserting task runners; retry
        # the snapshot instead of racing the dict iteration
        items = []
        for _ in range(5):
            try:
                items = list(runner.task_runners.items())
                break
            except RuntimeError:
                continue
        tasks = {}
        for name, tr in items:
            tasks[name] = tr.stats()
        total_mem = sum(t.get("memory_bytes", 0) for t in tasks.values())
        total_cpu = sum(t.get("cpu_usec", 0) for t in tasks.values())
        return {"alloc_id": alloc_id, "tasks": tasks,
                "memory_bytes": total_mem, "cpu_usec": total_cpu}

    def alloc_restart(self, alloc_id: str, task: str = "") -> dict:
        """In-place restart of a live alloc's task(s) (reference:
        alloc_endpoint.go Restart via server->client forwarding)."""
        with self._runner_lock:
            runner = self.runners.get(alloc_id)
        if runner is None:
            raise KeyError(f"alloc {alloc_id} not running here")
        if task:
            targets = [task]
        else:
            # the runner thread may still be inserting task runners
            # (same race alloc_stats guards against)
            targets = []
            for _ in range(5):
                try:
                    targets = list(runner.task_runners.keys())
                    break
                except RuntimeError:
                    continue
        restarted = []
        for name in targets:
            tr = runner.task_runners.get(name)
            if tr is None:
                raise KeyError(f"task {name!r} not found in alloc")
            tr.restart()
            restarted.append(name)
        return {"restarted": restarted}

    def csi_create_volume(self, plugin_id: str, volume_id: str,
                          parameters=None) -> dict:
        """Dynamic provisioning through the controller plugin this node
        runs (reference: csi CreateVolume via a controller-capable
        client)."""
        if self.csi_manager is None:
            raise KeyError("no csi plugins on this node")
        plugin = self.csi_manager.plugins.get(plugin_id)
        if plugin is None:
            raise KeyError(f"no csi plugin {plugin_id!r} on this node")
        return plugin.create_volume(volume_id, parameters or {})

    def csi_delete_volume(self, plugin_id: str, volume_id: str) -> None:
        if self.csi_manager is None:
            raise KeyError("no csi plugins on this node")
        plugin = self.csi_manager.plugins.get(plugin_id)
        if plugin is None:
            raise KeyError(f"no csi plugin {plugin_id!r} on this node")
        plugin.delete_volume(volume_id)

    def alloc_signal(self, alloc_id: str, task: str,
                     sig: str = "SIGUSR1") -> dict:
        """Deliver a signal to a live task (reference: alloc_endpoint.go
        Signal via server->client forwarding)."""
        with self._runner_lock:
            runner = self.runners.get(alloc_id)
        if runner is None:
            raise KeyError(f"alloc {alloc_id} not running here")
        tr = runner.task_runners.get(task)
        if tr is None:
            raise KeyError(f"task {task!r} not found in alloc")
        if tr.handle is None or tr.driver is None:
            raise KeyError(f"task {task!r} has no live handle")
        tr.driver.signal_task(tr.handle, sig)
        return {"signalled": task, "signal": sig}

    def alloc_exec(self, alloc_id: str, task: str,
                   cmd: List[str], timeout: float = 10.0) -> dict:
        """One-shot command inside a live task's context (reference:
        `nomad alloc exec` / plugins/drivers ExecTask -- scoped to the
        non-interactive form: captured stdout/stderr + exit code)."""
        with self._runner_lock:
            runner = self.runners.get(alloc_id)
        if runner is None:
            raise KeyError(f"alloc {alloc_id} not running here")
        tr = runner.task_runners.get(task)
        if tr is None:
            raise KeyError(f"task {task!r} not found in alloc")
        if tr.handle is None or tr.driver is None:
            raise KeyError(f"task {task!r} has no live handle")
        return tr.driver.exec_task(tr.handle, tr.env, tr.task_dir, cmd,
                                   timeout=timeout)

    def fs_logs(self, alloc_id: str, task: str, log_type: str = "stdout",
                offset: int = 0, limit: int = 1 << 20) -> bytes:
        """Rotated log frames for a task, sliced WITHOUT loading the full
        history (reference: fs_endpoint.go logs path:
        alloc/logs/<task>.<type>.<index>). A NEGATIVE offset tails: the
        last |offset| bytes of the concatenated frames (the reference's
        origin="end" semantics), clamped by limit."""
        import os
        if log_type not in ("stdout", "stderr"):
            raise ValueError(f"invalid log type {log_type!r}")
        log_dir = self._safe_path(alloc_id, "alloc/logs")

        def frame_idx(name: str) -> int:
            try:
                return int(name.rsplit(".", 1)[1])
            except ValueError:
                return 0

        # numeric rotation order: .2 before .10 (lexicographic would
        # scramble content past ten frames)
        frames = sorted(
            (f for f in os.listdir(log_dir)
             if f.startswith(f"{task}.{log_type}.")),
            key=frame_idx)
        if offset < 0:
            total = sum(os.path.getsize(os.path.join(log_dir, f))
                        for f in frames)
            offset = max(0, total + offset)
        out = []
        pos, want = 0, max(0, limit)
        skip = max(0, offset)
        for frame in frames:
            path = os.path.join(log_dir, frame)
            size = os.path.getsize(path)
            if pos + size <= skip:
                pos += size
                continue
            with open(path, "rb") as f:
                f.seek(max(0, skip - pos))
                chunk = f.read(want)
            out.append(chunk)
            want -= len(chunk)
            pos += size
            skip = max(skip, pos)
            if want <= 0:
                break
        return b"".join(out)

    # -- host stats (reference: client/hoststats/) ---------------------
    def client_stats(self) -> dict:
        if not hasattr(self, "_hoststats"):
            from .hoststats import HostStatsCollector
            self._hoststats = HostStatsCollector(self.data_dir)
        stats = self._hoststats.collect()
        stats["node_id"] = self.node.id
        with self._runner_lock:
            stats["allocs_running"] = len([
                r for r in self.runners.values()
                if r.client_status == "running"])
        return stats

    # -- watch loop (reference: watchAllocations :2280) ----------------
    def _watch_allocations(self) -> None:
        while not self._shutdown.is_set():
            if self._frozen.is_set():
                time.sleep(0.05)
                continue
            try:
                allocs, index = self.conn.pull_allocs(
                    self.node.id, self._last_index, timeout=1.0)
            except Exception:   # noqa: BLE001
                time.sleep(0.2)
                continue
            self._last_index = index
            self._run_allocs(allocs)

    def _run_allocs(self, allocs: List[Allocation]) -> None:
        """Diff desired vs running (reference: runAllocs :2538)."""
        desired = {a.id: a for a in allocs}
        updates: List[Allocation] = []
        with self._runner_lock:
            known = dict(self.runners)
        # stop/evict + server-side removals
        for alloc_id, runner in known.items():
            a = desired.get(alloc_id)
            if a is None:
                # server no longer tracks it: destroy (reference: alloc GC)
                runner.destroy(timeout=2.0)
                with self._runner_lock:
                    self.runners.pop(alloc_id, None)
                self._services_registered.discard(alloc_id)
                self.state_db.delete_alloc(alloc_id)
            elif a.desired_status != ALLOC_DESIRED_RUN and \
                    runner.client_status not in (ALLOC_CLIENT_COMPLETE,
                                                 ALLOC_CLIENT_FAILED):
                runner.stop(timeout=5.0)
                updates.append(runner.client_update())
        # new allocations
        for alloc_id, a in desired.items():
            if alloc_id in known or a.terminal_status() or \
                    a.client_terminal_status():
                continue
            if a.desired_status != ALLOC_DESIRED_RUN:
                continue
            runner = AllocRunner(
                a, self.drivers, self.data_dir, node=self.node,
                on_update=self._on_runner_update,
                identity_signer=self.identity_signer,
                secrets_fetcher=self.secrets_fetcher,
                device_manager=self.device_manager,
                csi_manager=self.csi_manager,
                csi_volume_info=self.conn.csi_volume,
                network_manager=self._get_network_manager)
            with self._runner_lock:
                self.runners[alloc_id] = runner
            self.state_db.put_alloc(alloc_id, a.modify_index)
            runner.start()
        if updates:
            self._push_updates(updates)
        self._gc_terminal_runners()

    # -- runner callbacks ----------------------------------------------
    def _on_runner_update(self, runner: AllocRunner) -> None:
        for name, tr in runner.task_runners.items():
            self.state_db.put_task_state(runner.alloc.id, name,
                                         tr.state, tr.handle)
        # native service discovery: register once the alloc is running
        # (deregistration is the server's terminal-status sweep)
        self._reconcile_services()
        self._push_updates([runner.client_update()])

    def _push_updates(self, updates: List[Allocation]) -> None:
        if self._frozen.is_set():
            return
        try:
            self.conn.update_allocs(updates)
        except Exception:   # noqa: BLE001
            pass

    # -- deployment health (reference: health_hook + allochealth) ------
    def _health_loop(self) -> None:
        while not self._shutdown.wait(0.1):
            if self._frozen.is_set():
                continue
            with self._runner_lock:
                runners = list(self.runners.values())
            for r in runners:
                if not r.alloc.deployment_id or \
                        r.deployment_health is not None:
                    continue
                min_healthy = 0.05
                if r.alloc.job is not None:
                    tg = r.alloc.job.lookup_task_group(r.alloc.task_group)
                    upd = (tg.update if tg and tg.update
                           else r.alloc.job.update)
                    if upd is not None:
                        min_healthy = upd.min_healthy_time_s
                decided = r.check_health(min_healthy)
                if decided is not None:
                    self._push_updates([r.client_update()])

    # -- heartbeatstop (reference: client/heartbeatstop.go) ------------
    def _heartbeatstop_loop(self) -> None:
        while not self._shutdown.wait(0.2):
            lost_for = time.time() - self._last_ok_heartbeat
            with self._runner_lock:
                runners = list(self.runners.values())
            for r in runners:
                tg = (r.alloc.job.lookup_task_group(r.alloc.task_group)
                      if r.alloc.job else None)
                stop_after = (tg.stop_after_client_disconnect_s
                              if tg else None)
                if stop_after is not None and lost_for >= stop_after and \
                        r.client_status not in (ALLOC_CLIENT_COMPLETE,
                                                ALLOC_CLIENT_FAILED):
                    r.stop(timeout=5.0)

    # -- client GC (reference: client/gc.go AllocGarbageCollector) -----
    def _gc_terminal_runners(self) -> None:
        with self._runner_lock:
            terminal = [(aid, r) for aid, r in self.runners.items()
                        if r.client_status in (ALLOC_CLIENT_COMPLETE,
                                               ALLOC_CLIENT_FAILED)
                        and r.wait(timeout=0)]
            excess = len(terminal) - MAX_TERMINAL_RUNNERS
            victims = terminal[:excess] if excess > 0 else []
            for aid, _ in victims:
                self.runners.pop(aid, None)
                self._services_registered.discard(aid)
        for aid, runner in victims:
            runner.destroy(timeout=1.0)
            self.state_db.delete_alloc(aid)
