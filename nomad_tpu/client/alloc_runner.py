"""AllocRunner: per-allocation lifecycle over its TaskRunners.

Semantic parity with /root/reference/client/allocrunner/ (alloc_runner.go:353
Run; hook pipeline alloc_runner_hooks.go -- allocdir, network, upstream
allocs, checks, health health_hook.go; task lifecycle ordering
tasklifecycle/ -- prestart hooks run before main tasks, leader failure
kills followers; client alloc status aggregation alloc_runner.go
clientStatus derivation).
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..structs import (
    AllocDeploymentStatus, Allocation,
    ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED, ALLOC_CLIENT_PENDING,
    ALLOC_CLIENT_RUNNING,
)
from .allocdir import AllocDir
from .drivers import DriverRegistry, TASK_STATE_DEAD, TASK_STATE_RUNNING
from .task_runner import TaskRunner, TaskState


class AllocRunner:
    """(reference: client/allocrunner/alloc_runner.go)"""

    def __init__(self, alloc: Allocation, drivers: DriverRegistry,
                 data_dir: str, node=None,
                 on_update: Optional[Callable[["AllocRunner"], None]] = None,
                 identity_signer=None, secrets_fetcher=None,
                 device_manager=None, csi_manager=None,
                 csi_volume_info=None, network_manager=None):
        self.alloc = alloc
        self.drivers = drivers
        self.node = node
        self.on_update = on_update
        self.identity_signer = identity_signer
        self.secrets_fetcher = secrets_fetcher
        self.device_manager = device_manager
        self.csi_manager = csi_manager
        self.csi_volume_info = csi_volume_info
        self.network_manager = network_manager
        self._network = None
        self.alloc_network = None
        self.csi_paths: Dict[str, str] = {}
        self._csi_attached: List[tuple] = []
        self._restored = False
        self.alloc_dir = AllocDir(data_dir, alloc.id)
        self.task_runners: Dict[str, TaskRunner] = {}
        self.client_status = ALLOC_CLIENT_PENDING
        self.client_description = ""
        self.deployment_health: Optional[bool] = None
        self._deployment_healthy_at = 0.0
        self._kill = threading.Event()
        self._done = threading.Event()
        self._update_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self.run, daemon=True, name=f"alloc-{self.alloc.id[:8]}")
        self._thread.start()

    def run(self) -> None:
        """(reference: alloc_runner.go:353 Run -- pre-run hooks, task
        runners by lifecycle phase, post-run)."""
        try:
            self.alloc_dir.build()      # allocdir hook
        except OSError as e:
            self._set_status(ALLOC_CLIENT_FAILED, f"allocdir: {e}")
            self._done.set()
            return
        tg = (self.alloc.job.lookup_task_group(self.alloc.task_group)
              if self.alloc.job else None)
        if tg is None or not tg.tasks:
            self._set_status(ALLOC_CLIENT_FAILED, "task group not found")
            self._done.set()
            return
        try:
            self._attach_csi_volumes(tg)
        except Exception as e:  # noqa: BLE001 -- plugin/volume failures
            self._set_status(ALLOC_CLIENT_FAILED, f"csi: {e}")
            self._detach_csi_volumes()
            self._done.set()
            self._notify()
            return
        try:
            self._setup_network(tg)     # network hook (bridge mode)
        except Exception as e:  # noqa: BLE001 -- netns/veth failures
            self._set_status(ALLOC_CLIENT_FAILED, f"network: {e}")
            self._detach_csi_volumes(tg_hint=tg)
            self._teardown_network()
            self._done.set()
            self._notify()
            return

        prestart = [t for t in tg.tasks if t.lifecycle
                    and t.lifecycle.get("hook") == "prestart"
                    and not t.lifecycle.get("sidecar")]
        sidecars = [t for t in tg.tasks if t.lifecycle
                    and t.lifecycle.get("sidecar")]
        main = [t for t in tg.tasks if not t.lifecycle]
        poststop = [t for t in tg.tasks if t.lifecycle
                    and t.lifecycle.get("hook") == "poststop"]

        def mk_runner(task) -> TaskRunner:
            tr = TaskRunner(
                self.alloc, task, self.drivers.get(task.driver),
                self.alloc_dir, node=self.node,
                restart_policy=tg.restart_policy,
                on_state_change=lambda _tr: self._on_task_change(),
                identity_signer=self.identity_signer,
                secrets_fetcher=self.secrets_fetcher,
                device_manager=self.device_manager,
                csi_paths=self.csi_paths)
            self.task_runners[task.name] = tr
            return tr

        # prestart (non-sidecar) tasks run to completion first
        # (reference: tasklifecycle coordinator)
        for task in prestart:
            tr = mk_runner(task)
            tr.start()
            tr.wait()
            if tr.state.failed:
                self._set_status(ALLOC_CLIENT_FAILED,
                                 f"prestart task {task.name} failed")
                self._detach_csi_volumes(tg_hint=tg)
                self._teardown_network()
                self._done.set()
                self._notify()
                return
        if self._kill.is_set():
            # stopped/destroyed during prestart: don't launch main tasks
            self._finalize_status(stopped=True)
            self._detach_csi_volumes(tg_hint=tg)
            self._teardown_network()
            self._done.set()
            self._notify()
            return
        for task in sidecars + main:
            mk_runner(task).start()
        if self._kill.is_set():
            # stop raced task launch: reap everything we just started
            for tr in self.task_runners.values():
                tr.kill()
        self._set_status(ALLOC_CLIENT_RUNNING, "tasks are running")
        self._notify()

        main_runners = [self.task_runners[t.name] for t in main]
        leader_names = {t.name for t in main if t.leader}
        while not self._kill.is_set():
            if all(tr.state.state == TASK_STATE_DEAD
                   for tr in main_runners):
                break
            # leader death kills followers (reference: task leader logic)
            if leader_names and any(
                    tr.state.state == TASK_STATE_DEAD
                    for tr in main_runners
                    if tr.task.name in leader_names):
                for tr in main_runners:
                    if tr.state.state != TASK_STATE_DEAD:
                        tr.kill()
                break
            time.sleep(0.05)
        # kill sidecars once main tasks are done; on stop/destroy kill
        # every still-running task, main included
        if self._kill.is_set():
            for tr in self.task_runners.values():
                if tr.state.state != TASK_STATE_DEAD:
                    tr.kill()
        for t in sidecars:
            self.task_runners[t.name].kill()
        for task in poststop:
            if not self._kill.is_set():
                tr = self.task_runners.get(task.name) or mk_runner(task)
                tr.start()
                tr.wait()
        self._finalize_status()
        self._detach_csi_volumes()
        self._teardown_network()
        self._done.set()
        self._notify()

    def destroy(self, timeout: float = 10.0) -> None:
        """Kill everything and remove the alloc dir
        (reference: alloc_runner Destroy)."""
        self._kill.set()
        for tr in self.task_runners.values():
            tr.kill()
        self._done.wait(timeout)
        # restored allocs never re-enter run(): destroy is their detach
        # point (paths are filesystem-deterministic, so this works even
        # when the attach happened before an agent restart)
        self._detach_csi_volumes(tg_hint=None)
        self._teardown_network()
        self.alloc_dir.destroy()

    def stop(self, timeout: float = 10.0) -> None:
        """Graceful stop, alloc dir kept for inspection."""
        self._kill.set()
        for tr in self.task_runners.values():
            tr.kill()
        self._done.wait(timeout)
        self._finalize_status(stopped=True)
        self._notify()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    # -- restore (reference: alloc_runner.go:455 Restore) --------------
    def restore(self, task_states: Dict[str, TaskState],
                handles: Dict[str, object]) -> bool:
        """Re-attach task runners to live tasks. Returns True if any task
        was recovered running."""
        self._restored = True
        self.alloc_dir.build()
        tg = (self.alloc.job.lookup_task_group(self.alloc.task_group)
              if self.alloc.job else None)
        if tg is None:
            return False
        try:
            # re-adopt the bridge netns (manager.create adopts an
            # existing namespace) so mapped ports come back up and the
            # terminal teardown can actually delete it
            self._setup_network(tg)
        except Exception:   # noqa: BLE001 -- degraded restore beats none
            pass
        any_live = False
        for task in tg.tasks:
            st = task_states.get(task.name)
            if st is None:
                continue
            tr = TaskRunner(
                self.alloc, task, self.drivers.get(task.driver),
                self.alloc_dir, node=self.node,
                restart_policy=tg.restart_policy,
                on_state_change=lambda _tr: self._on_task_change(),
                identity_signer=self.identity_signer,
                secrets_fetcher=self.secrets_fetcher,
                device_manager=self.device_manager,
                csi_paths=self.csi_paths)
            self.task_runners[task.name] = tr
            if tr.restore(st, handles.get(task.name)):
                any_live = True
        if any_live:
            self.client_status = ALLOC_CLIENT_RUNNING
            self._thread = threading.Thread(
                target=self._watch_restored, daemon=True,
                name=f"alloc-restored-{self.alloc.id[:8]}")
            self._thread.start()
        else:
            # nothing recovered: the alloc terminated while we were down --
            # the server must hear about it or it will never reschedule.
            # The network re-adopted above must come down with it or its
            # forwarders keep the alloc's host ports bound against the
            # replacement allocation
            self._finalize_status()
            self._teardown_network()
            self._done.set()
            self._notify()
        return any_live

    # -- bridge networking (reference: allocrunner/network_hook.go +
    #    networking_bridge_linux.go; redesign: client/netns.py) ---------
    def _setup_network(self, tg) -> None:
        """Create the alloc's network namespace when the group asks for
        bridge mode and this host supports it; tasks then launch inside
        it (drivers read alloc_dir.netns). Without support the alloc
        falls back to host networking, matching the dev-agent contract.
        """
        if self.network_manager is None or not tg.networks:
            return
        mode = getattr(tg.networks[0], "mode", "host") or "host"
        if mode != "bridge" and not mode.startswith("cni/"):
            return
        # network_manager is a FACTORY (Client._get_network_manager):
        # the capability probe only runs for bridge-mode groups
        manager = (self.network_manager() if callable(self.network_manager)
                   else self.network_manager)
        if manager is None:
            return
        self._network = manager
        ports = (self.alloc.allocated_resources.shared.ports
                 if self.alloc.allocated_resources is not None else [])
        net = manager.create(self.alloc.id, ports)
        self.alloc_network = net
        # drivers + taskenv read these off the shared alloc dir
        self.alloc_dir.netns = net.netns
        self.alloc_dir.alloc_ip = net.ip
        self.alloc_dir.gateway_ip = net.gateway

    def _teardown_network(self) -> None:
        if self.alloc_network is None or self._network is None:
            return
        try:
            self._network.destroy(self.alloc.id)
        except Exception:   # noqa: BLE001 -- best-effort teardown
            pass
        self.alloc_network = None

    # -- CSI volumes (reference: allocrunner/csi_hook.go: attach ONCE
    #    per alloc before tasks start, detach after they all stop) -----
    def _attach_csi_volumes(self, tg) -> None:
        if self.csi_manager is None:
            return
        referenced = {str(m.get("volume", ""))
                      for t in tg.tasks for m in (t.volume_mounts or [])}
        for name, vreq in (tg.volumes or {}).items():
            if vreq.type != "csi" or name not in referenced:
                continue
            if self.csi_volume_info is None:
                raise RuntimeError("no CSI volume lookup available")
            source = vreq.source_for(self.alloc.name)
            vol = self.csi_volume_info(self.alloc.namespace, source)
            if vol is None:
                raise RuntimeError(f"unknown CSI volume {source!r}")
            path = self.csi_manager.publish(
                vol.plugin_id, vol.id, self.alloc.id,
                self.alloc.node_id, vreq.read_only)
            self.csi_paths[name] = path
            self._csi_attached.append((vol.plugin_id, vol.id))

    def _detach_csi_volumes(self, tg_hint=None) -> None:
        """Best-effort by construction: detach runs on terminal paths
        (run end, watch-restored end, destroy) where a raise would leave
        a zombie alloc or kill the client's watch thread."""
        if self.csi_manager is None:
            return
        attached = self._csi_attached
        if not attached and self._restored:
            # restored alloc: the attach happened before an agent
            # restart; re-derive REFERENCED csi volumes from the job
            # spec (paths are filesystem-deterministic in the manager).
            # Allocs that already detached in run() have _restored
            # False and skip this entirely.
            tg = tg_hint or (self.alloc.job.lookup_task_group(
                self.alloc.task_group) if self.alloc.job else None)
            if tg is not None and self.csi_volume_info is not None:
                referenced = {str(m.get("volume", ""))
                              for t in tg.tasks
                              for m in (t.volume_mounts or [])}
                for name, vreq in (tg.volumes or {}).items():
                    if vreq.type != "csi" or name not in referenced:
                        continue
                    try:
                        vol = self.csi_volume_info(
                            self.alloc.namespace,
                            vreq.source_for(self.alloc.name))
                    except Exception:  # noqa: BLE001 -- server away
                        vol = None
                    if vol is not None:
                        attached.append((vol.plugin_id, vol.id))
        for plugin_id, vol_id in attached:
            try:
                self.csi_manager.unpublish(plugin_id, vol_id,
                                           self.alloc.id,
                                           self.alloc.node_id)
            except Exception:  # noqa: BLE001 -- best-effort detach
                pass
        self._csi_attached = []
        self.csi_paths = {}
        # once detached, never re-derive: destroy() after a restored
        # alloc's watch-thread detach must not issue a second round of
        # unpublish/unstage RPCs
        self._restored = False

    def _watch_restored(self) -> None:
        while not self._kill.is_set():
            if all(tr.state.state == TASK_STATE_DEAD
                   for tr in self.task_runners.values()):
                break
            time.sleep(0.05)
        self._finalize_status()
        self._detach_csi_volumes()
        self._teardown_network()
        self._done.set()
        self._notify()

    # -- health (reference: allocrunner/health_hook.go +
    #    allochealth/tracker.go) --------------------------------------
    def check_health(self, min_healthy_time: float) -> Optional[bool]:
        """None = still deciding; True/False once decided. Healthy when
        every task has been running for min_healthy_time; unhealthy when
        any task failed."""
        if self.deployment_health is not None:
            return self.deployment_health
        if self.client_status == ALLOC_CLIENT_FAILED or any(
                tr.state.failed for tr in self.task_runners.values()):
            self.deployment_health = False
            return False
        runners = list(self.task_runners.values())
        if not runners:
            return None
        now = time.time()
        if all(tr.state.state == TASK_STATE_RUNNING
               and tr.state.restarts == 0
               and now - tr.state.started_at >= min_healthy_time
               for tr in runners):
            self.deployment_health = True
            return True
        return None

    # -- status aggregation (reference: alloc_runner.go clientStatus) --
    def _on_task_change(self) -> None:
        with self._update_lock:
            runners = list(self.task_runners.values())
            if any(tr.state.state == TASK_STATE_DEAD and tr.state.failed
                   for tr in runners):
                self._set_status(ALLOC_CLIENT_FAILED, "a task failed")
            elif any(tr.state.state == TASK_STATE_RUNNING
                     for tr in runners):
                self._set_status(ALLOC_CLIENT_RUNNING, "tasks are running")
        self._notify()

    def _finalize_status(self, stopped: bool = False) -> None:
        runners = list(self.task_runners.values())
        if any(tr.state.failed for tr in runners) and not stopped:
            self._set_status(ALLOC_CLIENT_FAILED, "a task failed")
        else:
            self._set_status(ALLOC_CLIENT_COMPLETE,
                             "all tasks have completed")

    def _set_status(self, status: str, desc: str) -> None:
        self.client_status = status
        self.client_description = desc

    def _notify(self) -> None:
        if self.on_update is not None:
            try:
                self.on_update(self)
            except Exception:   # noqa: BLE001
                pass

    # -- snapshot for the server update (reference: Node.UpdateAlloc) --
    def client_update(self) -> Allocation:
        upd = Allocation(
            id=self.alloc.id, namespace=self.alloc.namespace,
            node_id=self.alloc.node_id, job_id=self.alloc.job_id,
            task_group=self.alloc.task_group)
        upd.client_status = self.client_status
        upd.client_description = self.client_description
        upd.task_states = {
            name: {"state": tr.state.state, "failed": tr.state.failed,
                   "restarts": tr.state.restarts,
                   "started_at": tr.state.started_at,
                   "finished_at": tr.state.finished_at}
            for name, tr in self.task_runners.items()}
        if self.client_status == ALLOC_CLIENT_FAILED:
            upd.client_terminal_time = time.time()
        if self.alloc.deployment_id and self.deployment_health is not None:
            upd.deployment_status = AllocDeploymentStatus(
                healthy=self.deployment_health, timestamp=time.time(),
                # health reports must not erase the canary marking the
                # scheduler placed (the reconciler's promotion bookkeeping
                # and the watcher's canary counts key on it)
                canary=(self.alloc.deployment_status.canary
                        if self.alloc.deployment_status is not None
                        else False))
        return upd
