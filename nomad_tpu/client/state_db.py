"""Client state persistence: alloc/task runner state surviving restarts.

Semantic parity with /root/reference/client/state/ (boltdb state db of
alloc runner + task runner state and driver handles; restore on agent boot
re-attaches to live tasks, client.go:1215 restoreState). JSON-file-backed
here; one file per client data dir, atomic replace on write.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

from .drivers import TaskHandle
from .task_runner import TaskEvent, TaskState


class StateDB:
    """(reference: client/state/db.go StateDB interface)"""

    def __init__(self, data_dir: str):
        self.path = os.path.join(data_dir, "client_state.json")
        self._lock = threading.Lock()
        self._data: dict = {"allocs": {}, "node_id": ""}
        if os.path.exists(self.path):
            try:
                with open(self.path, encoding="utf-8") as fh:
                    self._data = json.load(fh)
            except (json.JSONDecodeError, OSError):
                pass

    def _flush(self) -> None:
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(self._data, fh, separators=(",", ":"))
        os.replace(tmp, self.path)

    # -- node identity -------------------------------------------------
    def put_node_id(self, node_id: str) -> None:
        with self._lock:
            self._data["node_id"] = node_id
            self._flush()

    def node_id(self) -> str:
        with self._lock:
            return self._data.get("node_id", "")

    # -- alloc/task state ----------------------------------------------
    def put_alloc(self, alloc_id: str, modify_index: int) -> None:
        with self._lock:
            rec = self._data["allocs"].setdefault(
                alloc_id, {"tasks": {}})
            rec["modify_index"] = modify_index
            self._flush()

    def put_task_state(self, alloc_id: str, task_name: str,
                       state: TaskState,
                       handle: Optional[TaskHandle]) -> None:
        with self._lock:
            rec = self._data["allocs"].setdefault(
                alloc_id, {"tasks": {}})
            rec["tasks"][task_name] = {
                "state": {
                    "state": state.state, "failed": state.failed,
                    "restarts": state.restarts,
                    "started_at": state.started_at,
                    "finished_at": state.finished_at,
                    "events": [{"type": e.type, "time": e.time,
                                "details": e.details}
                               for e in state.events[-5:]],
                },
                "handle": None if handle is None else {
                    "task_id": handle.task_id, "driver": handle.driver,
                    "pid": handle.pid, "started_at": handle.started_at,
                    "driver_state": handle.driver_state,
                },
            }
            self._flush()

    def delete_alloc(self, alloc_id: str) -> None:
        with self._lock:
            self._data["allocs"].pop(alloc_id, None)
            self._flush()

    def alloc_ids(self) -> List[str]:
        with self._lock:
            return list(self._data["allocs"].keys())

    def get_alloc_tasks(self, alloc_id: str
                        ) -> Dict[str, tuple]:
        """-> {task_name: (TaskState, TaskHandle|None)}"""
        with self._lock:
            rec = self._data["allocs"].get(alloc_id, {"tasks": {}})
            out = {}
            for name, t in rec["tasks"].items():
                s = t["state"]
                state = TaskState(
                    state=s["state"], failed=s["failed"],
                    restarts=s["restarts"], started_at=s["started_at"],
                    finished_at=s["finished_at"],
                    events=[TaskEvent(type=e["type"], time=e["time"],
                                      details=e["details"])
                            for e in s.get("events", [])])
                h = t.get("handle")
                handle = None if h is None else TaskHandle(
                    task_id=h["task_id"], driver=h["driver"],
                    pid=h["pid"], started_at=h["started_at"],
                    driver_state=h.get("driver_state", {}))
                out[name] = (state, handle)
            return out
