"""OCI / docker image handling for the container driver.

Reference analog: drivers/docker/driver.go:1 (image pull + container
create) and docklog/docklog.go:1 (the log pipeline). The redesign keeps
the driver daemonless: images arrive as artifacts -- an OCI image-layout
directory (`oci-layout` + `index.json` + `blobs/`), a docker-archive tar
(`docker save` output), or a plain rootfs dir/tar -- and are flattened
into a per-task rootfs by applying layers in order with OCI whiteout
semantics. The image config's Env/Entrypoint/Cmd/WorkingDir participate
in command assembly exactly like dockerd's. Logs need no separate
pipeline: the payload's stdout/stderr are the task's log files already
(the reference needs docklog because dockerd owns the stream).

Registry pulls are deliberately OFF by default: the default deployment
has no egress, and an image fetched at task start is a supply-chain
liability the artifact path avoids. `registry://` image references
raise unless NOMAD_TPU_IMAGE_PULL=1 opts in; with the opt-in, the
native OCI distribution v2 puller (client/registry.py: manifest
negotiation, anonymous Bearer token auth, digest-verified blobs) pulls
into a scratch image-layout that flattens through the same
unpack_oci_layout path as file-shipped layouts.
"""
from __future__ import annotations

import gzip
import json
import os
import shutil
import tarfile
from dataclasses import dataclass, field
from typing import List, Optional

WHITEOUT_PREFIX = ".wh."
WHITEOUT_OPAQUE = ".wh..wh..opq"


@dataclass
class ImageConfig:
    """The runtime half of an OCI image config
    (application/vnd.oci.image.config.v1+json)."""

    env: List[str] = field(default_factory=list)
    entrypoint: List[str] = field(default_factory=list)
    cmd: List[str] = field(default_factory=list)
    working_dir: str = ""

    def argv(self, task_command: str, task_args: List[str]) -> List[str]:
        """Docker's command assembly: a task command REPLACES Cmd (and
        clears Entrypoint only when the task says so via command);
        otherwise Entrypoint + Cmd run."""
        if task_command:
            return [task_command] + list(task_args)
        argv = list(self.entrypoint) + list(self.cmd)
        if task_args:
            # args without command: docker semantics replace Cmd
            argv = list(self.entrypoint) + list(task_args)
        return argv


class ImageError(Exception):
    pass


def detect_format(image: str) -> str:
    """'oci-layout' | 'docker-archive' | 'rootfs-dir' | 'rootfs-tar'
    | 'registry'."""
    if image.startswith("registry://") or image.startswith("docker://"):
        return "registry"
    if os.path.isdir(image):
        if os.path.exists(os.path.join(image, "oci-layout")):
            return "oci-layout"
        return "rootfs-dir"
    if os.path.isfile(image):
        if _tar_has_member(image, "manifest.json"):
            return "docker-archive"
        if _tar_has_member(image, "oci-layout"):
            return "oci-layout-tar"
        return "rootfs-tar"
    raise ImageError(f"container image not found: {image}")


def _tar_has_member(path: str, name: str) -> bool:
    try:
        with tarfile.open(path) as tf:
            try:
                tf.getmember(name)
                return True
            except KeyError:
                return False
    except (tarfile.TarError, OSError):
        return False


def _safe_join(root: str, name: str) -> str:
    """Containment check that also RESOLVES symlinks: a lower layer can
    plant `evil -> /etc` and a later layer reference `evil/...` -- the
    name itself stays inside the rootfs while the real path escapes, so
    lexical normpath alone would let a tampered artifact delete or write
    host files (whiteout markers follow the resolved path)."""
    dest = os.path.normpath(os.path.join(root, name.lstrip("/")))
    realroot = os.path.realpath(root)
    if not dest.startswith(os.path.normpath(root) + os.sep) \
            and dest != os.path.normpath(root):
        raise ImageError(f"layer member escapes rootfs: {name!r}")
    real_parent = os.path.realpath(os.path.dirname(dest))
    if real_parent != realroot \
            and not real_parent.startswith(realroot + os.sep):
        raise ImageError(
            f"layer member traverses a symlink out of the rootfs: "
            f"{name!r}")
    return dest


def apply_layer(rootfs: str, layer_tar) -> None:
    """Extract one layer onto rootfs with OCI whiteout handling
    (image-spec layer.md): `.wh.<name>` deletes <name> from lower
    layers; `.wh..wh..opq` makes the directory opaque (drops all lower
    content)."""
    members = layer_tar.getmembers()
    regular = []
    for m in members:
        base = os.path.basename(m.name)
        parent = os.path.dirname(m.name)
        if base == WHITEOUT_OPAQUE:
            target = _safe_join(rootfs, parent)
            # the opaque TARGET itself may be a planted symlink: resolve
            # it before emptying the directory it points at
            realroot = os.path.realpath(rootfs)
            real_target = os.path.realpath(target)
            if real_target != realroot \
                    and not real_target.startswith(realroot + os.sep):
                raise ImageError(
                    f"opaque whiteout traverses a symlink out of the "
                    f"rootfs: {m.name!r}")
            if os.path.isdir(target):
                for entry in os.listdir(target):
                    full = os.path.join(target, entry)
                    (shutil.rmtree if os.path.isdir(full)
                     and not os.path.islink(full) else os.remove)(full)
            continue
        if base.startswith(WHITEOUT_PREFIX):
            victim = _safe_join(
                rootfs, os.path.join(parent, base[len(WHITEOUT_PREFIX):]))
            if os.path.isdir(victim) and not os.path.islink(victim):
                shutil.rmtree(victim, ignore_errors=True)
            elif os.path.lexists(victim):
                os.remove(victim)
            continue
        regular.append(m)
    # extract one member at a time, re-validating the resolved path
    # right before each write: a single layer can plant a symlink and
    # then name members THROUGH it, which a pre-pass over the whole
    # member list cannot see (the symlink isn't on disk yet)
    for m in regular:
        dest = _safe_join(rootfs, m.name)
        # type changes between layers displace the lower entry: a file
        # over a directory removes the tree, a directory over a file
        # removes the file
        if os.path.lexists(dest):
            lower_is_dir = (os.path.isdir(dest)
                            and not os.path.islink(dest))
            if not m.isdir() and lower_is_dir:
                shutil.rmtree(dest, ignore_errors=True)
            elif not m.isdir():
                os.remove(dest)
            elif m.isdir() and not lower_is_dir:
                os.remove(dest)
        layer_tar.extract(m, rootfs, filter="tar")


def _open_layer(path: str):
    """tarfile over a possibly-gzipped layer blob."""
    f = open(path, "rb")
    magic = f.read(2)
    f.seek(0)
    if magic == b"\x1f\x8b":
        return tarfile.open(fileobj=gzip.GzipFile(fileobj=f))  # noqa: SIM115
    return tarfile.open(fileobj=f)


def _parse_config_blob(raw: bytes) -> ImageConfig:
    doc = json.loads(raw or b"{}")
    cfg = doc.get("config") or {}
    return ImageConfig(
        env=list(cfg.get("Env") or []),
        entrypoint=list(cfg.get("Entrypoint") or []),
        cmd=list(cfg.get("Cmd") or []),
        working_dir=str(cfg.get("WorkingDir") or ""))


def unpack_oci_layout(layout_dir: str, rootfs: str) -> ImageConfig:
    """Flatten an OCI image-layout directory into rootfs."""
    try:
        index = json.load(open(os.path.join(layout_dir, "index.json")))
    except (OSError, ValueError) as e:
        raise ImageError(f"bad OCI layout: {e}") from e

    def blob(digest: str) -> str:
        algo, _, hexd = digest.partition(":")
        path = os.path.join(layout_dir, "blobs", algo, hexd)
        if not os.path.isfile(path):
            raise ImageError(f"missing blob {digest}")
        return path

    manifests = index.get("manifests") or []
    if not manifests:
        raise ImageError("OCI index has no manifests")
    manifest = json.load(open(blob(manifests[0]["digest"])))
    if "manifests" in manifest:         # nested index (multi-platform)
        manifest = json.load(open(blob(manifest["manifests"][0]["digest"])))
    config = ImageConfig()
    if manifest.get("config", {}).get("digest"):
        config = _parse_config_blob(
            open(blob(manifest["config"]["digest"]), "rb").read())
    os.makedirs(rootfs, exist_ok=True)
    for layer in manifest.get("layers") or []:
        with _open_layer(blob(layer["digest"])) as tf:
            apply_layer(rootfs, tf)
    return config


def unpack_docker_archive(archive: str, rootfs: str,
                          scratch: str) -> ImageConfig:
    """Flatten a `docker save` tar into rootfs."""
    extract = os.path.join(scratch, "docker-archive")
    shutil.rmtree(extract, ignore_errors=True)
    os.makedirs(extract)
    with tarfile.open(archive) as tf:
        tf.extractall(extract, filter="tar")
    try:
        manifest = json.load(open(os.path.join(extract, "manifest.json")))
    except (OSError, ValueError) as e:
        raise ImageError(f"bad docker archive: {e}") from e
    if not manifest:
        raise ImageError("docker archive manifest is empty")
    entry = manifest[0]
    config = ImageConfig()
    cfg_name = entry.get("Config")
    if cfg_name and os.path.isfile(os.path.join(extract, cfg_name)):
        config = _parse_config_blob(
            open(os.path.join(extract, cfg_name), "rb").read())
    os.makedirs(rootfs, exist_ok=True)
    for layer_name in entry.get("Layers") or []:
        with _open_layer(os.path.join(extract, layer_name)) as tf:
            apply_layer(rootfs, tf)
    shutil.rmtree(extract, ignore_errors=True)
    return config


def materialize(image: str, rootfs: str, scratch: str) -> ImageConfig:
    """Flatten any supported image reference into ``rootfs`` (which must
    not exist yet); returns the image's runtime config."""
    fmt = detect_format(image)
    if fmt == "registry":
        if os.environ.get("NOMAD_TPU_IMAGE_PULL", "") != "1":
            raise ImageError(
                "registry pulls are disabled (set NOMAD_TPU_IMAGE_PULL=1 "
                "and provide egress); ship the image as an OCI layout or "
                "docker-archive artifact instead")
        from .registry import pull
        layout = os.path.join(scratch, "registry-pull")
        shutil.rmtree(layout, ignore_errors=True)
        pull(image, layout)
        try:
            return unpack_oci_layout(layout, rootfs)
        finally:
            shutil.rmtree(layout, ignore_errors=True)
    if fmt == "rootfs-dir":
        shutil.copytree(image, rootfs, symlinks=True)
        return ImageConfig()
    if fmt == "rootfs-tar":
        os.makedirs(rootfs, exist_ok=True)
        with tarfile.open(image) as tf:
            tf.extractall(rootfs, filter="tar")
        return ImageConfig()
    if fmt == "oci-layout":
        return unpack_oci_layout(image, rootfs)
    if fmt == "oci-layout-tar":
        extract = os.path.join(scratch, "oci-layout")
        shutil.rmtree(extract, ignore_errors=True)
        os.makedirs(extract)
        with tarfile.open(image) as tf:
            tf.extractall(extract, filter="tar")
        try:
            return unpack_oci_layout(extract, rootfs)
        finally:
            shutil.rmtree(extract, ignore_errors=True)
    if fmt == "docker-archive":
        return unpack_docker_archive(image, rootfs, scratch)
    raise ImageError(f"unsupported image format {fmt!r}")
