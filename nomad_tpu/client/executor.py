"""Isolated task executor: namespaces + chroot + cgroups.

Semantic parity with /root/reference/drivers/shared/executor
(executor_linux.go:35 LibcontainerExecutor): the exec/container drivers'
payloads run in their own mount+PID namespaces, chrooted into the task
sandbox with read-only binds of the host toolchain (the reference's
allocdir chroot file map, client/allocdir/fs_linux.go), with cpu/memory
cgroup limits applied before exec. Implemented over util-linux unshare(1)
plus a generated launcher script instead of libcontainer: the launcher
joins its cgroup FIRST (echo $$ > cgroup.procs, so every descendant
inherits the limits -- no add-pid race), then builds the mount tree,
mounts a fresh /proc for the PID namespace, pivots via chroot and execs
the payload.

Degrades cleanly: IsolationCaps probes root + unshare + cgroups at
runtime; callers fall back to plain fork/exec when isolation is
unavailable (same contract the reference's non-Linux executor has).
"""
from __future__ import annotations

import os
import shlex
import shutil
import subprocess
from dataclasses import dataclass
from typing import Dict, List, Optional

from .cgroups import Cgroup, CgroupManager

# Host paths bind-mounted read-only into every exec chroot (reference:
# client/allocdir/fs_linux.go chrootEnv defaults).
DEFAULT_CHROOT_BINDS = ["/bin", "/sbin", "/usr", "/lib", "/lib64", "/etc",
                        "/dev"]


@dataclass
class IsolationCaps:
    namespaces: bool
    cgroups: bool
    cgroup_version: int

    @property
    def any(self) -> bool:
        return self.namespaces or self.cgroups


_caps: Optional[IsolationCaps] = None


def probe_caps(cgroup_root: Optional[str] = None) -> IsolationCaps:
    """Detect what isolation this host supports (cached)."""
    global _caps
    if _caps is not None and cgroup_root is None:
        return _caps
    ns = False
    if os.geteuid() == 0 and shutil.which("unshare") \
            and shutil.which("chroot"):
        try:
            rc = subprocess.run(
                ["unshare", "--mount", "--pid", "--fork", "true"],
                capture_output=True, timeout=10).returncode
            ns = rc == 0
        except (subprocess.SubprocessError, OSError):
            ns = False
    mgr = (CgroupManager(cgroup_root) if cgroup_root else CgroupManager())
    cg = mgr.available()
    caps = IsolationCaps(namespaces=ns, cgroups=cg,
                         cgroup_version=mgr.version)
    if cgroup_root is None:
        _caps = caps
    return caps


def _sh_quote(parts: List[str]) -> str:
    return " ".join(shlex.quote(p) for p in parts)


def build_launcher(root: str, argv: List[str], env: Dict[str, str],
                   cgroup: Optional[Cgroup], binds: List[str],
                   workdir: str = "/local") -> str:
    """The script run inside the fresh mount+PID namespaces. Mount changes
    are invisible to the host (private propagation) and vanish with the
    namespace."""
    lines = ["#!/bin/sh", "set -e"]
    if cgroup is not None:
        for p in cgroup.paths:
            lines.append(f"echo $$ > {shlex.quote(os.path.join(p, 'cgroup.procs'))}")
    # private propagation so binds never leak to the host mount table
    lines.append("mount --make-rprivate / 2>/dev/null || true")
    for bind in binds:
        # "src" mounts read-only at root+src; "src:target" mounts
        # read-write at root+target (sandbox dirs like /local, /alloc);
        # "src:target:ro" mounts read-only at root+target (volumes)
        if ":" in bind:
            src, _, rest = bind.partition(":")
            target, _, flag = rest.partition(":")
            writable = flag != "ro"
        else:
            src, target, writable = bind, bind, False
        if not os.path.exists(src):
            continue
        dst = root + target
        lines.append(f"mkdir -p {shlex.quote(dst)}")
        lines.append(f"mount --rbind {shlex.quote(src)} {shlex.quote(dst)}")
        if not writable and src != "/dev":
            # bind remounts must repeat the source's nosuid/nodev flags or
            # the kernel rejects them (EPERM); escalate through the flag
            # combos and FAIL the launch if none lands -- running a
            # root-privileged chroot with writable host binds is worse
            # than not starting
            q = shlex.quote(dst)
            lines.append(
                f"mount -o remount,ro,bind {q} 2>/dev/null || "
                f"mount -o remount,ro,nosuid,bind {q} 2>/dev/null || "
                f"mount -o remount,ro,nosuid,nodev,bind {q} 2>/dev/null"
                f" || exit 97")
    lines.append(f"mkdir -p {shlex.quote(root + '/proc')} "
                 f"{shlex.quote(root + '/tmp')}")
    lines.append(f"mount -t proc proc {shlex.quote(root + '/proc')}")
    # scrub inherited env; re-export only the task env
    exports = "".join(
        f"export {k}={shlex.quote(str(v))}\n" for k, v in env.items()
        if k.isidentifier())
    lines.append(exports.rstrip("\n"))
    # util-linux `unshare --fork` leaves SIGINT/SIGTERM set to SIG_IGN
    # in the forked child (the supervisor ignores them while waiting,
    # and dispositions are inherited across fork+exec) -- and POSIX sh
    # can neither trap nor reset a signal that was ignored on entry, so
    # a payload's `trap ... TERM` silently never fires and every
    # graceful stop escalates to SIGKILL.  GNU coreutils env
    # --default-signal resets the dispositions between unshare and the
    # payload; probe for support so non-GNU env degrades to the old
    # (ungraceful) behavior instead of failing the launch.
    exec_line = (f"exec chroot {shlex.quote(root)} /bin/sh -c "
                 + shlex.quote(
                     f"cd {shlex.quote(workdir)} 2>/dev/null || cd /; "
                     f"exec {_sh_quote(argv)}"))
    lines.append("if env --default-signal=SIGINT,SIGTERM true "
                 "2>/dev/null; then")
    lines.append("  " + exec_line.replace(
        "exec chroot", "exec env --default-signal=SIGINT,SIGTERM "
        "chroot", 1))
    lines.append("fi")
    lines.append(exec_line)
    return "\n".join(lines) + "\n"


def launch_isolated(task_id: str, argv: List[str], env: Dict[str, str],
                    root: str, launcher_dir: str,
                    stdout_path: Optional[str], stderr_path: Optional[str],
                    cpu_shares: int = 0, memory_mb: int = 0,
                    binds: Optional[List[str]] = None,
                    workdir: str = "/local",
                    cgroup_root: Optional[str] = None,
                    netns: Optional[str] = None):
    """Start the payload under namespaces+chroot+cgroups. Returns
    (Popen of the unshare supervisor, Cgroup or None). The Popen's pid is
    the reattach handle; killing its process group kills the namespace
    (unshare --kill-child ties the payload to the supervisor)."""
    mgr = CgroupManager(cgroup_root) if cgroup_root else CgroupManager()
    cgroup = None
    if mgr.available() and (cpu_shares > 0 or memory_mb > 0):
        cgroup = mgr.create(task_id, cpu_shares=cpu_shares,
                            memory_mb=memory_mb)
    script = build_launcher(root, argv, env, cgroup,
                            binds if binds is not None
                            else DEFAULT_CHROOT_BINDS, workdir)
    launcher = os.path.join(launcher_dir, f"launcher-{task_id[:8]}.sh")
    with open(launcher, "w") as f:
        f.write(script)
    os.chmod(launcher, 0o700)
    stdout = open(stdout_path, "ab") if stdout_path else subprocess.DEVNULL
    stderr = open(stderr_path, "ab") if stderr_path else subprocess.DEVNULL
    try:
        argv = ["unshare", "--mount", "--pid", "--fork", "--kill-child",
                "/bin/sh", launcher]
        if netns:
            # join the alloc's bridge network namespace first; the
            # mount/PID namespaces are still fresh per task
            argv = ["ip", "netns", "exec", netns] + argv
        proc = subprocess.Popen(
            argv,
            stdout=stdout, stderr=stderr, start_new_session=True,
            env={"PATH": "/usr/sbin:/usr/bin:/sbin:/bin"})
    except OSError:
        if cgroup is not None:
            cgroup.destroy()
        raise
    finally:
        for fh in (stdout, stderr):
            if hasattr(fh, "close"):
                fh.close()
    return proc, cgroup
