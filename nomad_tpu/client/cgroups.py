"""Cgroup editor: resource-limit enforcement for isolated tasks.

Semantic parity with /root/reference/client/lib/cgroupslib (the v1/v2
editor the executor uses) and the limits drivers/shared/executor applies
(executor_linux.go:35 region: cpu shares + memory limits via
libcontainer). Pure-file implementation: v2 (unified hierarchy) preferred,
v1 (split memory/cpu controllers) fallback -- this build environment
mounts v1 with a controller-less unified dir, so both paths are real.

The root is injectable so tests can drive the v2 path against a fake
filesystem even on a v1 host.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional

CGROUP_ROOT = "/sys/fs/cgroup"
PARENT = "nomad_tpu"


def _write(path: str, value: str) -> bool:
    try:
        with open(path, "w") as f:
            f.write(value)
        return True
    except OSError:
        return False


def _read(path: str) -> Optional[str]:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return None


def shares_to_weight(shares: int) -> int:
    """cgroup v1 cpu.shares [2, 262144] -> v2 cpu.weight [1, 10000]
    (the kernel's documented mapping, used by the reference's cpuparts)."""
    shares = max(2, min(int(shares), 262144))
    return 1 + ((shares - 2) * 9999) // 262142


class Cgroup:
    """One task's cgroup: v2 = a single directory, v1 = one directory per
    controller."""

    def __init__(self, version: int, paths: List[str]):
        self.version = version
        self.paths = paths          # v2: [dir]; v1: [memory_dir, cpu_dir]

    def add_pid(self, pid: int) -> bool:
        ok = True
        for p in self.paths:
            ok = _write(os.path.join(p, "cgroup.procs"), str(pid)) and ok
        return ok

    def procs(self) -> List[int]:
        out: List[int] = []
        for p in self.paths:
            raw = _read(os.path.join(p, "cgroup.procs")) or ""
            for line in raw.splitlines():
                if line.strip():
                    out.append(int(line))
            break               # one controller's view is authoritative
        return out

    def stats(self) -> Dict[str, int]:
        """Memory bytes + cpu usage usec, whichever files exist."""
        out: Dict[str, int] = {}
        for p in self.paths:
            cur = _read(os.path.join(p, "memory.current")) \
                or _read(os.path.join(p, "memory.usage_in_bytes"))
            if cur is not None:
                out["memory_bytes"] = int(cur)
            stat = _read(os.path.join(p, "cpu.stat"))
            if stat:
                for line in stat.splitlines():
                    k, _, v = line.partition(" ")
                    if k == "usage_usec":
                        out["cpu_usec"] = int(v)
            usage = _read(os.path.join(p, "cpuacct.usage"))
            if usage is not None:
                out["cpu_usec"] = int(usage) // 1000
        return out

    def kill(self) -> None:
        """Kill every process in the group (v2: cgroup.kill; v1: signal
        each pid)."""
        import signal
        for p in self.paths:
            if _write(os.path.join(p, "cgroup.kill"), "1"):
                return
        for pid in self.procs():
            try:
                os.kill(pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    def destroy(self) -> None:
        for p in self.paths:
            try:
                os.rmdir(p)
            except OSError:
                pass


class CgroupManager:
    """Creates per-task cgroups under <root>/.../nomad_tpu/<scope>."""

    def __init__(self, root: str = CGROUP_ROOT):
        self.root = root
        self.version = self._detect()

    def _detect(self) -> int:
        """v2 iff the root itself is the unified hierarchy WITH usable
        controllers (a bare hybrid-mode unified mount doesn't count)."""
        ctrl = _read(os.path.join(self.root, "cgroup.controllers"))
        if ctrl is not None and ("memory" in ctrl or "cpu" in ctrl):
            return 2
        if os.path.isdir(os.path.join(self.root, "memory")) \
                or os.path.isdir(os.path.join(self.root, "cpu")):
            return 1
        return 0

    def available(self) -> bool:
        if self.version == 0:
            return False
        probe = (os.path.join(self.root, PARENT) if self.version == 2
                 else os.path.join(self.root, "memory", PARENT))
        try:
            os.makedirs(probe, exist_ok=True)
            return True
        except OSError:
            return False

    def create(self, scope: str, cpu_shares: int = 0,
               memory_mb: int = 0) -> Optional[Cgroup]:
        """Create + configure a task cgroup; None when unsupported."""
        if self.version == 2:
            path = os.path.join(self.root, PARENT, scope)
            try:
                os.makedirs(path, exist_ok=True)
            except OSError:
                return None
            # enable controllers on the parent for child delegation
            _write(os.path.join(self.root, PARENT, "cgroup.subtree_control"),
                   "+cpu +memory")
            if memory_mb > 0:
                _write(os.path.join(path, "memory.max"),
                       str(memory_mb * 1024 * 1024))
            if cpu_shares > 0:
                _write(os.path.join(path, "cpu.weight"),
                       str(shares_to_weight(cpu_shares)))
            return Cgroup(2, [path])
        if self.version == 1:
            paths = []
            mem = os.path.join(self.root, "memory", PARENT, scope)
            cpu = os.path.join(self.root, "cpu", PARENT, scope)
            try:
                os.makedirs(mem, exist_ok=True)
                os.makedirs(cpu, exist_ok=True)
            except OSError:
                return None
            if memory_mb > 0:
                _write(os.path.join(mem, "memory.limit_in_bytes"),
                       str(memory_mb * 1024 * 1024))
            if cpu_shares > 0:
                _write(os.path.join(cpu, "cpu.shares"),
                       str(max(2, int(cpu_shares))))
            paths = [mem, cpu]
            return Cgroup(1, paths)
        return None
