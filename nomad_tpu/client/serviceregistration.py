"""Workload service registration: builds catalog entries for an alloc's
group + task services.

Semantic parity with /root/reference/client/serviceregistration/ (the
"nomad" provider path, nsd/): when a workload starts, its services with
provider "nomad" register in the server's native catalog; they deregister
when the alloc stops. Address comes from the node, port from the alloc's
allocated ports by label (reference: serviceregistration/workload.go).
"""
from __future__ import annotations

from typing import List

from ..structs import Allocation, Node, ServiceRegistration


def _node_address(node: Node) -> str:
    for key in ("unique.network.ip-address", "network.ip-address"):
        addr = (node.attributes or {}).get(key)
        if addr:
            return addr
    return "127.0.0.1"


def _port_by_label(alloc: Allocation, label: str) -> int:
    """Resolve a service's port label against the alloc's assigned ports
    (reference: taskenv port interpolation over AllocatedPorts)."""
    if not label:
        return 0
    res = alloc.allocated_resources
    networks = []
    if res is not None:
        # group network ports land in shared.ports (AllocatedPortMapping)
        for pm in res.shared.ports or []:
            if pm.label == label:
                return pm.value
        networks.extend(res.shared.networks or [])
        for tr in res.tasks.values():
            networks.extend(tr.networks or [])
    for net in networks:
        for port in list(net.reserved_ports or []) + \
                list(net.dynamic_ports or []):
            if port.label == label:
                return port.value
    return 0


def build_registrations(alloc: Allocation, node: Node
                        ) -> List[ServiceRegistration]:
    """Registrations for every provider="nomad" service of the alloc's
    group and its tasks. Deterministic ids (alloc+service name) so
    re-registration after a client restart is idempotent
    (reference: serviceregistration id scheme `_nomad-task-<alloc>-...`)."""
    job = alloc.job
    if job is None:
        return []
    tg = job.lookup_task_group(alloc.task_group)
    if tg is None:
        return []
    services = [(s, "group") for s in (tg.services or [])]
    for task in tg.tasks:
        services.extend((s, task.name) for s in (task.services or []))
    out: List[ServiceRegistration] = []
    for svc, scope in services:
        if svc.provider != "nomad":
            continue   # consul-provider services are out of catalog scope
        out.append(ServiceRegistration(
            id=f"_nomad-{scope}-{alloc.id}-{svc.name}",
            service_name=svc.name,
            namespace=job.namespace,
            node_id=alloc.node_id or node.id,
            datacenter=node.datacenter,
            job_id=job.id,
            alloc_id=alloc.id,
            provider="nomad",
            tags=list(svc.tags),
            address=_node_address(node),
            port=_port_by_label(alloc, svc.port_label)))
    return out
