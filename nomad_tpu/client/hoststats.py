"""Host resource usage collection from /proc.

Semantic parity with /root/reference/client/hoststats/ (HostStatsCollector:
cpu, memory, disk, uptime sampled on an interval and served through the
ClientStats endpoint). Linux /proc readers with graceful fallbacks so the
collector never breaks the agent on exotic hosts.
"""
from __future__ import annotations

import os
import time
from typing import Optional


def _read_meminfo() -> dict:
    out = {}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 2:
                    out[parts[0].rstrip(":")] = int(parts[1]) * 1024
    except OSError:
        pass
    return out


def _read_cpu_ticks() -> Optional[tuple]:
    """-> (busy, total) jiffies across all cpus."""
    try:
        with open("/proc/stat") as f:
            fields = f.readline().split()[1:]
        nums = [int(x) for x in fields]
        idle = nums[3] + (nums[4] if len(nums) > 4 else 0)
        total = sum(nums)
        return total - idle, total
    except (OSError, ValueError, IndexError):
        return None


class HostStatsCollector:
    """(reference: hoststats/host.go HostStatsCollector.Collect)"""

    def __init__(self, data_dir: str = "/"):
        self.data_dir = data_dir
        self._prev_ticks = _read_cpu_ticks()

    def collect(self) -> dict:
        mem = _read_meminfo()
        ticks = _read_cpu_ticks()
        cpu_pct = 0.0
        if ticks and self._prev_ticks and ticks[1] > self._prev_ticks[1]:
            busy = ticks[0] - self._prev_ticks[0]
            total = ticks[1] - self._prev_ticks[1]
            cpu_pct = 100.0 * busy / total if total else 0.0
        self._prev_ticks = ticks
        try:
            st = os.statvfs(self.data_dir)
            disk_total = st.f_blocks * st.f_frsize
            disk_free = st.f_bavail * st.f_frsize
        except OSError:
            disk_total = disk_free = 0
        return {
            "timestamp": time.time(),
            "cpu_percent": round(cpu_pct, 2),
            "memory": {
                "total": mem.get("MemTotal", 0),
                "available": mem.get("MemAvailable", 0),
                "used": max(0, mem.get("MemTotal", 0)
                            - mem.get("MemAvailable", 0)),
            },
            "disk": {"total": disk_total, "free": disk_free,
                     "used": max(0, disk_total - disk_free)},
            "uptime_s": self._host_uptime(),
        }

    @staticmethod
    def _host_uptime() -> float:
        try:
            with open("/proc/uptime") as f:
                return round(float(f.read().split()[0]), 1)
        except (OSError, ValueError, IndexError):
            return 0.0
