"""Simulated client agent: the node-side loop with a mock driver.

Semantic parity (behavioral) with /root/reference/client/client.go
(registerAndHeartbeat :1734, watchAllocations :2280, runAllocs :2538) and
the scriptable mock driver (drivers/mock/driver.go:117: run_for /
exit_code / start_error / start_block_for). In-process for the dev agent
topology; the real multi-host client speaks the same server API surface.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..structs import (
    AllocDeploymentStatus, Allocation, Node,
    ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED, ALLOC_CLIENT_PENDING,
    ALLOC_CLIENT_RUNNING, ALLOC_DESIRED_RUN,
)


def _parse_duration(val) -> float:
    if val is None:
        return 0.0
    if isinstance(val, (int, float)):
        return float(val)
    s = str(val).strip()
    if s.endswith("ms"):
        return float(s[:-2]) / 1000.0
    if s.endswith("s"):
        return float(s[:-1])
    if s.endswith("m"):
        return float(s[:-1]) * 60.0
    try:
        return float(s)
    except ValueError:
        return 0.0


class _TaskState:
    __slots__ = ("started_at", "run_for", "will_fail", "done", "healthy_at",
                 "health_reported")

    def __init__(self, started_at, run_for, will_fail,
                 min_healthy_time: float = 0.05):
        self.started_at = started_at
        self.run_for = run_for
        self.will_fail = will_fail
        self.done = False
        # min_healthy_time gate (reference: UpdateStrategy.MinHealthyTime +
        # allocrunner health_hook); the sim caps it to keep tests fast
        self.healthy_at = started_at + min(min_healthy_time, 0.3)
        self.health_reported = False


class SimClient(threading.Thread):
    """One simulated node agent."""

    def __init__(self, server, node: Node, poll_interval: float = 0.05):
        super().__init__(daemon=True, name=f"client-{node.name}")
        self.server = server
        self.node = node
        self.poll_interval = poll_interval
        self._stop_ev = threading.Event()
        self._frozen = threading.Event()   # simulate network partition
        self._tasks: Dict[str, _TaskState] = {}
        self._last_hb = 0.0

    # -- failure injection -------------------------------------------------
    def freeze(self) -> None:
        """Stop heartbeating + status updates (simulates partition/crash)."""
        self._frozen.set()

    def thaw(self) -> None:
        self._frozen.clear()

    def stop(self) -> None:
        self._stop_ev.set()

    # ----------------------------------------------------------------------
    def run(self) -> None:
        self.server.register_node(self.node)
        while not self._stop_ev.is_set():
            if not self._frozen.is_set():
                self._heartbeat_if_due()
                self._reconcile_allocs()
            time.sleep(self.poll_interval)

    def _heartbeat_if_due(self) -> None:
        ttl = self.server.heartbeat_ttl
        now = time.time()
        if now - self._last_hb >= max(ttl / 3.0, 0.05):
            self.server.heartbeat(self.node.id)
            self._last_hb = now

    def _reconcile_allocs(self) -> None:
        """The client's pull loop: diff desired state vs running tasks
        (reference: watchAllocations + runAllocs)."""
        allocs = self.server.state.allocs_by_node(self.node.id)
        updates: List[Allocation] = []
        now = time.time()
        for alloc in allocs:
            if alloc.desired_status == ALLOC_DESIRED_RUN:
                if alloc.client_status == ALLOC_CLIENT_PENDING and \
                        alloc.id not in self._tasks:
                    updates.extend(self._start_alloc(alloc, now))
                elif alloc.id in self._tasks:
                    upd = self._advance_task(alloc, now)
                    if upd is not None:
                        updates.append(upd)
            else:
                # desired stop/evict -> kill the task
                if alloc.id in self._tasks and \
                        not alloc.client_terminal_status():
                    self._tasks.pop(alloc.id, None)
                    updates.append(self._mk_update(
                        alloc, ALLOC_CLIENT_COMPLETE))
        if updates:
            self.server.update_allocs_from_client(updates)

    def _start_alloc(self, alloc: Allocation, now: float) -> List[Allocation]:
        cfg = {}
        min_healthy = 0.05
        if alloc.job is not None:
            tg = alloc.job.lookup_task_group(alloc.task_group)
            if tg is not None:
                if tg.tasks:
                    cfg = tg.tasks[0].config or {}
                update = tg.update or alloc.job.update
                if update is not None:
                    min_healthy = update.min_healthy_time_s
        if cfg.get("start_error"):
            return [self._mk_update(alloc, ALLOC_CLIENT_FAILED,
                                    desc=str(cfg["start_error"]))]
        run_for = _parse_duration(cfg.get("run_for"))
        will_fail = int(cfg.get("exit_code", 0) or 0) != 0
        self._tasks[alloc.id] = _TaskState(now, run_for, will_fail,
                                           min_healthy)
        # native service discovery: the workload's services enter the
        # catalog as it starts (reference: client serviceregistration)
        from .serviceregistration import build_registrations
        regs = build_registrations(alloc, self.node)
        if regs:
            self.server.upsert_services(regs)
        return [self._mk_update(alloc, ALLOC_CLIENT_RUNNING)]

    def _advance_task(self, alloc: Allocation,
                      now: float) -> Optional[Allocation]:
        ts = self._tasks.get(alloc.id)
        if ts is None or ts.done:
            return None
        if ts.run_for > 0 and now - ts.started_at >= ts.run_for:
            ts.done = True
            self._tasks.pop(alloc.id, None)
            status = (ALLOC_CLIENT_FAILED if ts.will_fail
                      else ALLOC_CLIENT_COMPLETE)
            return self._mk_update(alloc, status)
        if alloc.client_status != ALLOC_CLIENT_RUNNING:
            return self._mk_update(alloc, ALLOC_CLIENT_RUNNING)
        # deployment health only after surviving min_healthy_time, and
        # never for tasks doomed to fail (reference: health_hook watches
        # the running task for the min window before reporting)
        if (not ts.health_reported and not ts.will_fail
                and now >= ts.healthy_at and alloc.deployment_id):
            ts.health_reported = True
            return self._mk_update(alloc, ALLOC_CLIENT_RUNNING, healthy=True)
        return None

    def _mk_update(self, alloc: Allocation, status: str, healthy: bool = False,
                   desc: str = "") -> Allocation:
        upd = Allocation(id=alloc.id, namespace=alloc.namespace,
                         node_id=alloc.node_id, job_id=alloc.job_id,
                         task_group=alloc.task_group)
        upd.client_status = status
        upd.client_description = desc
        upd.task_states = {"task": {"state": status}}
        if status == ALLOC_CLIENT_FAILED:
            upd.client_terminal_time = time.time()
        if alloc.deployment_id and (healthy or status == ALLOC_CLIENT_FAILED):
            upd.deployment_status = AllocDeploymentStatus(
                healthy=(status != ALLOC_CLIENT_FAILED),
                timestamp=time.time(),
                canary=(alloc.deployment_status.canary
                        if alloc.deployment_status is not None else False))
        return upd
