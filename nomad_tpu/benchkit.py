"""Tier-shaped benchmark/parity worlds (BASELINE.md config tiers 1-5).

Shared by tests/test_parity_scale.py (CI scale, CPU) and bench.py (full
scale, TPU): the same generators build the same world shapes at any size,
so the parity CI gates exactly what the bench measures
(reference sweep analog: scheduler/benchmarks/benchmarks_test.go:36-79).

Tiers (BASELINE.md "Targets"):
  1: 3-TG service job (web/api/worker) on a 5-node dev cluster
  2: batch allocs over uniform nodes, binpack vs spread algorithm
  3: C1M-replay shape -- cpu+mem+dynamic-port asks, node-class mix,
     kernel/class constraints
  4: C2M shape -- affinity + anti-affinity (implicit) + spread mixes
  5: preemption-heavy -- high utilization, priority tiers (see
     tests/test_preemption_tpu.py for the parity harness)
"""
from __future__ import annotations

import itertools
import os
import random
from typing import Dict, List, Optional, Tuple

from . import mock
from .structs import (
    Affinity, Constraint, DeviceRequest, NetworkResource,
    NodeDeviceResource, Port, PreemptionConfig, SchedulerConfiguration,
    Spread, SpreadTarget,
    ALLOC_CLIENT_RUNNING,
)

RACK_COUNT = 25   # reference sweep uses {10,25,50,75} racks


def dispatch_health_stamp(platform: str) -> dict:
    """Breaker/guard/dispatch state for bench artifacts.

    Round 5's official bench silently captured the CPU fallback after
    the tunnel wedged mid-round (VERDICT r5 weak #1): every artifact now
    carries an EXPLICIT ``degraded`` verdict plus the dispatch-layer
    state that justifies it, so a wedged tunnel can never masquerade as
    a chip result. ``degraded`` is False only for a healthy TPU round;
    otherwise it names the reason.
    """
    from .solver import guard

    st = guard.state()
    if platform != "tpu":
        degraded = "cpu-fallback"
    elif st["checked"] and not st["ok"]:
        degraded = "backend-unavailable"
    elif st["breaker"]["state"] != guard.BREAKER_CLOSED:
        degraded = f"breaker-{st['breaker']['state']}"
    else:
        degraded = False
    cc = st.get("const_cache", {})
    pipe = st.get("dispatch_pipeline", {})
    pc = st.get("pack_cache", {})
    ar = st.get("pack_arena", {})
    return {
        "degraded": degraded,
        "dispatch_state": {
            "breaker": st["breaker"]["state"],
            "breaker_trips": st["breaker"]["trips"],
            "breaker_recoveries": st["breaker"]["recoveries"],
            "last_probe": st["breaker"]["last_probe"],
            "dispatch_ok": st["dispatch"]["ok"],
            "dispatch_timeout": st["dispatch"]["timeout"],
            "dispatch_error": st["dispatch"]["error"],
            "host_fallback_dispatches": st["host_fallback_dispatches"],
            "backend_ok": st["ok"],
        },
        # transfer layer (ISSUE 2): shipped bytes + const-cache hit
        # rate belong in every artifact so the delta-streaming claim is
        # measured, not inferred
        "transfer_state": {
            "dispatch_bytes_total": st["dispatch"].get("bytes_total", 0),
            "const_cache_hits": cc.get("hits", 0),
            "const_cache_misses": cc.get("misses", 0),
            "const_cache_bytes_saved": cc.get("bytes_saved_total", 0),
            "const_cache_resident_bytes": cc.get("resident_bytes", 0),
            "dispatch_depth": pipe.get("depth", 1),
            # host pack layer (ISSUE 4): the warm-path claim -- packing
            # amortized across the snapshot -- is measured, not inferred
            "pack_cache_hits": pc.get("hits", 0),
            "pack_cache_misses": pc.get("misses", 0),
            "pack_usage_base_hits": pc.get("usage_base_hits", 0),
            "pack_arena_reuses": ar.get("reuses", 0),
            "pack_arena_resident_bytes": ar.get("resident_bytes", 0),
            "pipeline_staged_total": pipe.get("staged_total", 0),
        },
    }


def jitcheck_stamp() -> dict:
    """Dispatch-discipline fields for bench artifacts (ISSUE 10):
    steady-state retraces, hot-path host syncs and x64 leaks observed
    during the run. All zero when the sanitizer is off (the default)
    -- the regress gate (scripts/check_bench_regress.py) only bites on
    a round that RAN the sanitizer and found violations, and on any
    round where a previously-zero field goes positive."""
    from . import jitcheck

    st = jitcheck.state()
    return {
        "jitcheck_enabled": st["enabled"],
        "jit_retrace_count": st["retrace_count"],
        "jit_host_sync_count": st["host_sync_count"],
        "jit_x64_leaks": st["x64_leak_count"],
    }


def statecheck_stamp() -> dict:
    """Snapshot-isolation fields for bench artifacts (ISSUE 11): torn
    reads, aliasing writes, journal gaps, write skews and stale memos
    observed during the run. All zero when the sanitizer is off (the
    default) -- the regress gate (scripts/check_bench_regress.py) only
    bites on a round that RAN the sanitizer and found violations, and
    on any round where a previously-zero field goes positive."""
    from . import statecheck

    st = statecheck.state()
    return {
        "statecheck_enabled": st["enabled"],
        "state_torn_reads": st["torn_read_count"],
        "state_aliasing_writes": st["aliasing_write_count"],
        "state_journal_gaps": st["journal_gap_count"],
        "state_write_skews": st["write_skew_count"],
        "state_stale_memos": st["stale_memo_count"],
    }


def shardcheck_stamp() -> dict:
    """Sharding-discipline fields for bench artifacts (ISSUE 15):
    spec drift vs the parallel/mesh.py registry, implicit transfers
    into mesh callables and collective-budget excess observed during
    the run. All zero when the sanitizer is off (the default) -- the
    regress gate (scripts/check_bench_regress.py) only bites on a
    round that RAN the sanitizer and found violations, and on any
    round where a previously-zero field goes positive."""
    from . import shardcheck

    st = shardcheck.state()
    return {
        "shardcheck_enabled": st["enabled"],
        "shard_spec_drift": st["spec_drift_count"],
        "shard_implicit_xfer": st["implicit_xfer_count"],
        "shard_collective_excess": st["collective_excess_count"],
    }


def xferobs_stamp() -> dict:
    """Transfer-observatory artifact fields (ISSUE 13): ledger byte
    decomposition totals, byte parity vs the dispatch_bytes counter
    (must be 0), and the live tunnel-model fit -- so payload-bytes
    regressions and link-model drift are gated per round
    (scripts/check_bench_regress.py direction rows) instead of
    rediscovered by manual capture."""
    from .solver import xferobs

    return xferobs.bench_fields()


def delta_stream_stamp() -> dict:
    """Delta-streaming artifact fields (ISSUE 20): version-chain
    promotions/reuses vs wholesale fallbacks and the cumulative delta
    payload, so the journal->device scatter path's win (and any
    regression back to re-shipping full tables) is read off every
    artifact. Gated by scripts/check_bench_regress.py direction rows."""
    from .solver import constcache

    cc = constcache.stats()
    return {
        "delta_stream_enabled": bool(
            cc.get("delta_stream_enabled", False)),
        "delta_promotions": cc.get("delta_promotions", 0),
        "delta_reuses": cc.get("delta_reuses", 0),
        "delta_fallbacks": cc.get("delta_fallbacks", 0),
        "delta_bytes_total": cc.get("delta_bytes_total", 0),
        "delta_chain_resident_bytes": cc.get("chain_resident_bytes", 0),
    }


def artifact_stamp(repo_root: Optional[str] = None) -> dict:
    """Provenance stamp for every bench artifact so trend tooling can
    line BENCH_rNN.json files up without guessing (ISSUE 7 satellite):

    - ``round_id``: ``BENCH_ROUND_ID`` env when set, else derived as
      max(existing BENCH_rNN round numbers) + 1;
    - ``git_sha``: HEAD at run time (None outside a git checkout);
    - ``run_id``: a wall-clock-free monotonic sequence number persisted
      in ``.bench_run_seq`` next to the artifacts -- two runs of the
      same round stay distinguishable and orderable even on machines
      with a wandering clock.

    Never raises: a bench run must not die on provenance."""
    import re
    import subprocess

    root = repo_root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    sha = None
    try:
        sha = subprocess.check_output(
            ["git", "-C", root, "rev-parse", "--short", "HEAD"],
            stderr=subprocess.DEVNULL, timeout=10).decode().strip() or None
    except Exception:  # noqa: BLE001 -- not a git checkout / no git
        pass
    round_id = os.environ.get("BENCH_ROUND_ID")
    if not round_id:
        seen = [0]
        try:
            for name in os.listdir(root):
                m = re.match(r"BENCH_r(\d+)", name)
                if m:
                    seen.append(int(m.group(1)))
        except OSError:
            pass
        round_id = f"r{max(seen) + 1:02d}"
    seq_path = os.path.join(root, ".bench_run_seq")
    run_id = 0
    try:
        with open(seq_path, encoding="utf-8") as f:
            run_id = int(f.read().strip() or 0)
    except (OSError, ValueError):
        pass
    run_id += 1
    try:
        with open(seq_path, "w", encoding="utf-8") as f:
            f.write(str(run_id))
    except OSError:
        pass
    return {"round_id": round_id, "git_sha": sha, "run_id": run_id}


def quality_stamp() -> dict:
    """Quality/saturation artifact fields (ISSUE 7): fragmentation,
    shadow-audit drift/mismatch counts and per-stage busy shares from
    the process-global observatory.  Call while the measured Server is
    still attached (its shutdown detaches the observatory)."""
    from .server.quality import observatory

    return observatory.bench_fields()


def export_chrome_trace(path: str) -> "str | None":
    """Write the flight recorder's retained eval traces as a
    chrome://tracing / Perfetto JSON artifact (the per-eval span view
    that explains WHERE a bench round's latency went), meant to land
    next to the BENCH_*.json line. Returns the written path, or None
    when tracing is off or nothing was retained -- artifact emission
    must never fail a bench run."""
    import json

    from .server.tracing import trace_enabled, tracer
    from .solver import xferobs

    if not trace_enabled():
        return None
    doc = tracer.chrome_trace()
    if not doc["traceEvents"]:
        # no retained eval spans -> no artifact (the counter tracks
        # annotate the span view; they are not a trace by themselves)
        return None
    # Perfetto counter tracks (ISSUE 13): shipped bytes / resident
    # bytes / in-flight depth per retained dispatch record, rendered as
    # counter lanes under the eval spans
    doc["traceEvents"].extend(xferobs.counter_events())
    try:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(doc, f)
    except OSError:
        return None
    return path


def run_scale_northstar(target_allocs: int, n_nodes: int = 10000,
                        e_evals: int = 32, per_eval: int = 2000,
                        round_timeout_s: float = 300.0,
                        log=None) -> dict:
    """The north-star-scale shape: drive ``target_allocs`` LIVE
    allocations through the full production batched pipeline (Server +
    BatchWorker eval coalescing + SolveBarrier fused dispatch +
    group-commit plan applier) WITHOUT draining between rounds, so the
    state store, alloc table and applier carry the accumulated fleet the
    whole way -- the number the ROADMAP's north star is phrased in,
    measured instead of extrapolated.

    Scale hygiene baked in: the AllocTable is preallocated to the target
    (no doubling copies under the store lock), per-placement
    explainability stubs are pruned (NOMAD_TPU_LEAN_ALLOC_METRICS), and
    the peak RSS rides the returned dict so the memory ceiling is part
    of the artifact. The same code path shrinks to a tier-1 smoke at a
    few thousand allocs (tests/test_scale_northstar.py).

    Returns {"allocs", "wall_s", "placements_per_sec", "rss_mb",
    "rounds", "truncated"}."""
    import os
    import resource
    import time

    from . import mock
    from .server import Server
    from .structs import SchedulerConfiguration

    def say(msg):
        if log is not None:
            log(msg)

    allocs_per_node = max(1, (target_allocs + n_nodes - 1) // n_nodes)
    rounds = max(1, (target_allocs + e_evals * per_eval - 1)
                 // (e_evals * per_eval))
    prev_lean = os.environ.get("NOMAD_TPU_LEAN_ALLOC_METRICS")
    os.environ["NOMAD_TPU_LEAN_ALLOC_METRICS"] = "1"
    server = Server(num_workers=e_evals, heartbeat_ttl=3600.0,
                    eval_batching=True, batch_width=e_evals)
    server.state.set_scheduler_config(
        SchedulerConfiguration(scheduler_algorithm="tpu-binpack"))
    server.state.preallocate_allocs(
        int(target_allocs * 1.1) + e_evals * per_eval)
    server.start()
    placed_total = 0
    truncated = False
    try:
        # fleet provisioned so the target fits with ~40% headroom at
        # 10cpu/32mb/10disk per alloc (tiny asks: the point is the alloc
        # COUNT, not per-alloc weight)
        for i in range(n_nodes):
            n = mock.node()
            n.id = f"nstar-node-{i:06d}"
            n.node_resources.cpu.cpu_shares = int(allocs_per_node * 14)
            n.node_resources.memory.memory_mb = int(allocs_per_node * 45)
            n.node_resources.disk.disk_mb = int(allocs_per_node * 14)
            n.compute_class()
            server.register_node(n)
        say(f"northstar: fleet up ({n_nodes} nodes, "
            f"{rounds} rounds x {e_evals}x{per_eval})")

        t0 = time.perf_counter()
        for r in range(rounds):
            jobs = []
            for i in range(e_evals):
                job = mock.job(id=f"nstar-{r:03d}-{i:02d}")
                tg = job.task_groups[0]
                tg.count = per_eval
                tg.ephemeral_disk.size_mb = 10
                tg.tasks[0].resources.cpu = 10
                tg.tasks[0].resources.memory_mb = 32
                jobs.append(job)
            for job in jobs:
                server.register_job(job)
            want = e_evals * per_eval
            deadline = time.time() + round_timeout_s
            placed = 0
            while time.time() < deadline:
                approx = sum(
                    server.state.num_allocs_by_job(job.namespace, job.id)
                    for job in jobs)
                if approx >= want:
                    placed = sum(
                        1 for job in jobs
                        for a in server.state.allocs_by_job(
                            job.namespace, job.id)
                        if a.desired_status == "run")
                    if placed >= want:
                        break
                time.sleep(0.05)
            else:
                placed = sum(
                    1 for job in jobs
                    for a in server.state.allocs_by_job(job.namespace,
                                                        job.id)
                    if a.desired_status == "run")
            placed_total += placed
            if placed < want:
                truncated = True
                say(f"northstar: round {r} TRUNCATED "
                    f"({placed}/{want}); stopping at {placed_total}")
                break
            # scale hygiene: the round's placements are LIVE for the
            # rest of the run -- freeze them into the permanent GC
            # generation so full collections (which JAX hooks with a
            # per-collection callback) stop re-walking millions of
            # immortal allocs
            import gc
            gc.collect()
            gc.freeze()
            if (r + 1) % 4 == 0 or r + 1 == rounds:
                dt_so_far = time.perf_counter() - t0
                say(f"northstar: {placed_total} live allocs after "
                    f"round {r + 1}/{rounds} "
                    f"({placed_total / dt_so_far:.0f}/s)")
        wall = time.perf_counter() - t0
    finally:
        if prev_lean is None:
            os.environ.pop("NOMAD_TPU_LEAN_ALLOC_METRICS", None)
        else:
            os.environ["NOMAD_TPU_LEAN_ALLOC_METRICS"] = prev_lean
        server.shutdown()
    rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    return {
        "allocs": placed_total,
        "wall_s": round(wall, 3),
        "placements_per_sec": round(placed_total / wall, 2) if wall
        else 0.0,
        "rss_mb": round(rss_mb, 1),
        "rounds": rounds,
        "truncated": truncated,
    }


def run_scale_churn(live_target: int, n_nodes: int = 10000,
                    e_evals: int = 32, per_eval: int = 2000,
                    rounds: int = 6, churn_jobs: int = 4,
                    flap_nodes: int = 2,
                    round_timeout_s: float = 300.0,
                    gc_watermark: Optional[int] = None,
                    log=None) -> dict:
    """Sustained-churn north star (ISSUE 6 / ROADMAP item 5): hold
    ~``live_target`` LIVE allocations while the pipeline absorbs
    continuous arrivals (new jobs), completions (deregister + client
    ack), and node flaps (down -> lost-alloc reschedule -> recovery
    through the flap damper) at steady state -- production traffic is
    churn, not a queue drained once. Every round ends with a GC pass
    under the terminal-alloc watermark plus table compaction, and a
    fold-parity check of the incremental delta memos against a full
    refold, so the run measures BOUNDED state, not accumulation.

    Reports p50/p99 submit->commit latency over the arrival jobs, RSS
    per round (growth across churn rounds is the leak signal; peak ru_
    maxrss alone can't show re-use), and ``parity_mismatch`` (must be
    0). The same code path shrinks to a tier-1 smoke
    (tests/test_scale_churn.py), mirroring test_scale_northstar's
    split; the full-scale run is bench.py ``time_scale_churn``."""
    import os
    import resource
    import time

    from . import mock
    from .server import Server
    from .structs import ALLOC_CLIENT_COMPLETE, SchedulerConfiguration

    def say(msg):
        if log is not None:
            log(msg)

    def rss_now_mb() -> float:
        try:
            with open("/proc/self/statm") as f:
                pages = int(f.read().split()[1])
            return pages * (resource.getpagesize() / 1048576.0)
        except (OSError, ValueError, IndexError):
            return (resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                    / 1024.0)

    allocs_per_node = max(1, (live_target + n_nodes - 1) // n_nodes)
    warmup_waves = max(1, (live_target + e_evals * per_eval - 1)
                       // (e_evals * per_eval))
    if gc_watermark is None:
        gc_watermark = max(1000, live_target // 4)
    prev_lean = os.environ.get("NOMAD_TPU_LEAN_ALLOC_METRICS")
    os.environ["NOMAD_TPU_LEAN_ALLOC_METRICS"] = "1"
    server = Server(num_workers=e_evals, heartbeat_ttl=3600.0,
                    eval_batching=True, batch_width=e_evals)
    server.state.set_scheduler_config(
        SchedulerConfiguration(scheduler_algorithm="tpu-binpack"))
    server.state.preallocate_allocs(
        int(live_target * 1.2) + e_evals * per_eval)
    server.start()
    truncated = False
    latencies_ms: list = []
    rss_rounds: list = []
    parity_mismatch = 0
    arrivals = completions = flaps = quarantine_deferrals = 0
    active_jobs: list = []      # insertion order = age order
    job_seq = 0

    def churn_job():
        nonlocal job_seq
        job = mock.job(id=f"churn-{job_seq:05d}")
        job_seq += 1
        tg = job.task_groups[0]
        tg.count = per_eval
        tg.ephemeral_disk.size_mb = 10
        tg.tasks[0].resources.cpu = 10
        tg.tasks[0].resources.memory_mb = 32
        return job

    def wait_placed(jobs, deadline):
        """Block until every job's allocs are placed; records per-job
        submit->commit latency. Returns False on timeout."""
        pending = {(j.namespace, j.id): t0 for j, t0 in jobs}
        while pending and time.time() < deadline:
            for key in list(pending):
                ns, jid = key
                if server.state.num_allocs_by_job(ns, jid) >= per_eval:
                    placed = sum(
                        1 for a in server.state.allocs_by_job(ns, jid)
                        if a.desired_status == "run")
                    if placed >= per_eval:
                        latencies_ms.append(
                            (time.perf_counter() - pending.pop(key))
                            * 1e3)
            if pending:
                time.sleep(0.02)
        return not pending

    try:
        # fleet with ~60% headroom: flapped nodes and in-flight
        # replacements need somewhere to land
        fleet_ids = []
        for i in range(n_nodes):
            n = mock.node()
            n.id = f"churn-node-{i:06d}"
            n.node_resources.cpu.cpu_shares = int(allocs_per_node * 16)
            n.node_resources.memory.memory_mb = int(allocs_per_node * 52)
            n.node_resources.disk.disk_mb = int(allocs_per_node * 16)
            n.compute_class()
            server.register_node(n)
            fleet_ids.append(n.id)
        say(f"churn: fleet up ({n_nodes} nodes); warming to "
            f"{live_target} live allocs")

        for w in range(warmup_waves):
            jobs = [churn_job() for _ in range(e_evals)]
            batch = []
            for job in jobs:
                t0 = time.perf_counter()
                server.register_job(job)
                batch.append((job, t0))
                active_jobs.append(job)
            if not wait_placed(batch, time.time() + round_timeout_s):
                truncated = True
                say(f"churn: warmup wave {w} TRUNCATED")
                break
        latencies_ms.clear()        # warmup is not steady state
        rss_rounds.append(round(rss_now_mb(), 1))
        # ISSUE-20 delta-stream leg: snapshot the version-chain and
        # transfer-ledger counters AFTER warmup so the reported
        # bytes-per-dispatch is the warm steady state (install-time
        # wholesale uploads are warmup, not the regime under test)
        from .solver import constcache as _cc
        from .solver import xferobs as _xo
        cc0 = _cc.stats()
        xo0 = _xo.state() if _xo.enabled() else {}

        flappy = fleet_ids[:flap_nodes]
        t_run0 = time.perf_counter()
        for r in range(rounds):
            if truncated:
                break
            # completions: the oldest jobs leave (deregister -> stop
            # eval), and their clients ack terminal
            leaving = active_jobs[:churn_jobs]
            active_jobs = active_jobs[churn_jobs:]
            for job in leaving:
                server.deregister_job(job.namespace, job.id)
                acks = []
                for a in server.state.allocs_by_job(job.namespace,
                                                    job.id):
                    upd = a.copy_skip_job()
                    upd.client_status = ALLOC_CLIENT_COMPLETE
                    upd.client_terminal_time = time.time()
                    acks.append(upd)
                server.update_allocs_from_client(acks)
                completions += len(acks)
            # flaps: the same nodes go down every round, so the flap
            # damper's escalating quarantine actually engages
            for nid in flappy:
                node = server.state.node_by_id(nid)
                if node is not None and node.ready():
                    server.update_node_status(nid, "down")
                    flaps += 1
            # arrivals replace the departed capacity
            batch = []
            for _ in range(churn_jobs):
                job = churn_job()
                t0 = time.perf_counter()
                server.register_job(job)
                batch.append((job, t0))
                active_jobs.append(job)
            arrivals += churn_jobs * per_eval
            if not wait_placed(batch, time.time() + round_timeout_s):
                truncated = True
                say(f"churn: round {r} TRUNCATED")
            # flapped nodes try to come back; quarantined ones are
            # deferred (they retry next round)
            for nid in flappy:
                node = server.state.node_by_id(nid)
                if node is not None and node.status == "down":
                    rem = server.flaps.quarantine_remaining(nid)
                    if rem > 0:
                        quarantine_deferrals += 1
                    server.heartbeat(nid)
            # bounded state: terminal sweep + watermark + compaction
            server.run_gc_once(threshold=0.0,
                               terminal_watermark=gc_watermark)
            parity_mismatch += \
                server.state.alloc_table.fold_parity_mismatch()
            rss_rounds.append(round(rss_now_mb(), 1))
            say(f"churn: round {r + 1}/{rounds} done "
                f"(rss {rss_rounds[-1]:.0f}MB, "
                f"parity_mismatch={parity_mismatch})")
        churn_wall = time.perf_counter() - t_run0
        # settle before reading: the final round's replacement
        # placements and stop-acks commit asynchronously, so an
        # immediate live count can race them a couple of allocs high
        # or low (the tier-1 smoke asserts EXACT target).  A bounded
        # poll until the count holds the target removes the race
        # without weakening the gate -- a genuinely accumulating or
        # leaking run never settles and still fails the assert.
        deadline = time.time() + 15.0
        while time.time() < deadline:
            live_now = sum(
                1 for j in active_jobs
                for a in server.state.allocs_by_job(j.namespace, j.id)
                if not a.terminal_status())
            if live_now == live_target:
                break
            time.sleep(0.05)
        cc1 = _cc.stats()
        xo1 = _xo.state() if _xo.enabled() else {}
        xfer_parity = abs(_xo.parity()) if _xo.enabled() else 0
    finally:
        if prev_lean is None:
            os.environ.pop("NOMAD_TPU_LEAN_ALLOC_METRICS", None)
        else:
            os.environ["NOMAD_TPU_LEAN_ALLOC_METRICS"] = prev_lean
        server.shutdown()

    live = sum(1 for j in active_jobs
               for a in server.state.allocs_by_job(j.namespace, j.id)
               if not a.terminal_status())
    terminal = sum(1 for a in server.state.allocs()
                   if a.terminal_status())
    lat = sorted(latencies_ms)

    def pct(p):
        if not lat:
            return 0.0
        return round(lat[min(len(lat) - 1, int(p * len(lat)))], 2)

    rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    out = {
        "live_allocs": live,
        "terminal_allocs": terminal,
        "rounds": rounds,
        "churn_wall_s": round(churn_wall, 3),
        "arrivals": arrivals,
        "completions": completions,
        "flaps": flaps,
        "quarantine_deferrals": quarantine_deferrals,
        "submit_commit_p50_ms": pct(0.50),
        "submit_commit_p99_ms": pct(0.99),
        "rss_mb_rounds": rss_rounds,
        "rss_growth_mb": round(rss_rounds[-1] - rss_rounds[0], 1)
        if len(rss_rounds) >= 2 else 0.0,
        "rss_mb": round(rss_mb, 1),
        "gc_watermark": gc_watermark,
        "parity_mismatch": parity_mismatch,
        "truncated": truncated,
    }
    # ISSUE-20 delta-stream leg: warm steady-state deltas over the
    # churn rounds only (warmup installs subtracted out).  dispatches
    # come off the transfer ledger; with NOMAD_TPU_XFEROBS=0 the
    # per-dispatch normalization is structurally absent and reported 0.
    n_disp = (xo1.get("dispatches", 0) or 0) - \
             (xo0.get("dispatches", 0) or 0)
    d_bytes = cc1["delta_bytes_total"] - cc0["delta_bytes_total"]
    shipped = (xo1.get("shipped_bytes_total", 0) or 0) - \
              (xo0.get("shipped_bytes_total", 0) or 0)
    out.update({
        "delta_stream_enabled": bool(cc1.get("delta_stream_enabled")),
        "delta_promotions": cc1["delta_promotions"]
        - cc0["delta_promotions"],
        "delta_reuses": cc1["delta_reuses"] - cc0["delta_reuses"],
        "delta_fallbacks": cc1["delta_fallbacks"]
        - cc0["delta_fallbacks"],
        "delta_bytes_per_dispatch": round(d_bytes / n_disp, 1)
        if n_disp else 0.0,
        "shipped_bytes_per_dispatch": round(shipped / n_disp, 1)
        if n_disp else 0.0,
        "xfer_ledger_parity": xfer_parity,
    })
    return out


def run_worker_scaling(pool_sizes=(1, 2, 4, 8), n_nodes: int = 2000,
                       jobs: int = 16, per_eval: int = 250,
                       timeout_s: float = 300.0, log=None) -> dict:
    """Crash-safe N-worker control plane scaling (ISSUE 16): the same
    end-to-end placement workload (``jobs`` jobs x ``per_eval`` allocs
    each) pushed through the supervised PLAIN worker pool at each size
    in ``pool_sizes``, reporting e2e placements/s per size at fold
    parity 0.  eval_batching stays OFF on purpose: the axis under test
    is scheduler-loop parallelism across N workers racing the
    group-commit applier (cross-worker serialization and all), not
    dispatch fusion -- the fused path has its own headline.  A size
    that cannot finish inside ``timeout_s`` marks the run truncated
    (never silently published as complete)."""
    import os
    import time as _time

    from . import mock
    from .server import Server

    def say(msg):
        if log is not None:
            log(msg)

    total = jobs * per_eval
    allocs_per_node = max(1, (total * 13 // 10 + n_nodes - 1)
                          // n_nodes)
    prev_lean = os.environ.get("NOMAD_TPU_LEAN_ALLOC_METRICS")
    os.environ["NOMAD_TPU_LEAN_ALLOC_METRICS"] = "1"
    pps: dict = {}
    walls: dict = {}
    parity_mismatch = 0
    truncated = False
    try:
        for size in pool_sizes:
            server = Server(num_workers=int(size), heartbeat_ttl=3600.0,
                            eval_batching=False)
            server.start()
            try:
                for i in range(n_nodes):
                    n = mock.node()
                    n.id = f"wscale-{size}-node-{i:06d}"
                    n.node_resources.cpu.cpu_shares = \
                        int(allocs_per_node * 16)
                    n.node_resources.memory.memory_mb = \
                        int(allocs_per_node * 52)
                    n.node_resources.disk.disk_mb = \
                        int(allocs_per_node * 16)
                    n.compute_class()
                    server.register_node(n)
                batch = []
                t0 = _time.perf_counter()
                for k in range(jobs):
                    job = mock.job(id=f"wscale-{size}-job-{k:04d}")
                    tg = job.task_groups[0]
                    tg.count = per_eval
                    tg.ephemeral_disk.size_mb = 10
                    tg.tasks[0].resources.cpu = 10
                    tg.tasks[0].resources.memory_mb = 32
                    server.register_job(job)
                    batch.append(job)
                deadline = _time.time() + timeout_s
                pending = {(j.namespace, j.id) for j in batch}
                while pending and _time.time() < deadline:
                    for key in list(pending):
                        ns, jid = key
                        placed = sum(
                            1 for a in server.state.allocs_by_job(ns,
                                                                  jid)
                            if a.desired_status == "run")
                        if placed >= per_eval:
                            pending.discard(key)
                    if pending:
                        _time.sleep(0.02)
                wall = _time.perf_counter() - t0
                if pending:
                    truncated = True
                    say(f"worker-scaling: pool={size} TRUNCATED "
                        f"({len(pending)}/{jobs} jobs unplaced after "
                        f"{timeout_s:.0f}s)")
                placed_total = total - len(pending) * per_eval
                walls[int(size)] = round(wall, 3)
                pps[int(size)] = round(placed_total / wall, 2) \
                    if wall > 0 else 0.0
                parity_mismatch += \
                    server.state.alloc_table.fold_parity_mismatch()
                say(f"worker-scaling: pool={size} -> "
                    f"{pps[int(size)]:.0f} placements/s "
                    f"({placed_total} placed in {wall:.2f}s, "
                    f"parity_mismatch={parity_mismatch})")
            finally:
                server.shutdown()
    finally:
        if prev_lean is None:
            os.environ.pop("NOMAD_TPU_LEAN_ALLOC_METRICS", None)
        else:
            os.environ["NOMAD_TPU_LEAN_ALLOC_METRICS"] = prev_lean
    base = pps.get(int(pool_sizes[0])) or 0.0
    best = max(pps.values()) if pps else 0.0
    return {
        "pool_sizes": [int(s) for s in pool_sizes],
        "placements_per_sec": pps,
        "wall_s": walls,
        "placed_per_size": total,
        "speedup_best_vs_1": round(best / base, 3) if base else 0.0,
        "parity_mismatch": parity_mismatch,
        "truncated": truncated,
    }


def make_fleet(rng: random.Random, h, n_nodes: int,
               racks: int = RACK_COUNT, gpus: bool = False) -> List:
    """Heterogeneous fleet: 3 machine classes, rack + datacenter spread
    attributes (the reference bench's rack axis). ``gpus`` equips every
    other node with an nvidia/gpu group of 2-4 instances (the BASELINE
    tier-5 'GPU device reservations' axis)."""
    nodes = []
    for i in range(n_nodes):
        node = mock.node()
        node.id = f"tier-node-{i:06d}"
        node.node_resources.cpu.cpu_shares = (4000, 8000, 16000)[i % 3]
        node.node_resources.memory.memory_mb = (8192, 16384, 32768)[i % 3]
        node.datacenter = f"dc{i % 2 + 1}"
        node.attributes["platform.rack"] = f"rack-{i % racks:03d}"
        if gpus and i % 2 == 0:
            n_inst = (2, 4)[i % 4 // 2]
            node.node_resources.devices = [NodeDeviceResource(
                vendor="nvidia", type="gpu", name="v100",
                instance_ids=[f"{node.id}-gpu-{k}"
                              for k in range(n_inst)])]
        node.compute_class()
        h.state.upsert_node(node)
        nodes.append(node)
    return nodes


def seed_utilization(rng: random.Random, h, nodes, frac: float,
                     priorities=(50,)) -> None:
    """Fill ~frac of each node's cpu with existing allocs."""
    for node in nodes:
        cap = node.node_resources.cpu.cpu_shares
        target = int(cap * frac)
        used = 0
        while used + 500 <= target:
            j = mock.job(priority=rng.choice(priorities))
            j.id = f"filler-{node.id}-{used}"
            j.task_groups[0].tasks[0].resources.cpu = 500
            j.task_groups[0].tasks[0].resources.memory_mb = rng.choice(
                [512, 1024])
            h.state.upsert_job(j)
            a = mock.alloc_for(j, node)
            a.client_status = ALLOC_CLIENT_RUNNING
            h.state.upsert_allocs([a])
            used += 500


def tier_job(tier: int, rng: random.Random, count: int):
    """The job each tier schedules."""
    job = mock.job(type="batch" if tier == 2 else "service")
    tg = job.task_groups[0]
    tg.count = count
    task = tg.tasks[0]
    task.resources.cpu = rng.choice([250, 500, 1000])
    task.resources.memory_mb = rng.choice([256, 512, 1024])

    if tier == 1:
        # BASELINE tier 1: 3-TG service job on a 5-node dev cluster --
        # the smallest end-to-end shape (web + api + worker, distinct
        # asks, one TG with dynamic ports)
        import copy as _copy
        tg.name = "web"
        tg.count = max(1, min(count, 3))
        tg.networks = [NetworkResource(dynamic_ports=[Port(label="http")])]
        for name, cnt, cpu, mem in (("api", 2, 500, 512),
                                    ("worker", 1, 1000, 1024)):
            tg2 = _copy.deepcopy(job.task_groups[0])
            tg2.name = name
            tg2.count = cnt
            tg2.networks = []
            tg2.tasks[0].resources.cpu = cpu
            tg2.tasks[0].resources.memory_mb = mem
            job.task_groups.append(tg2)
        return job

    if tier == 3:
        # C1M shape: ports + constraints (cpu+mem+port per BASELINE tier 3)
        tg.networks = [NetworkResource(
            dynamic_ports=[Port(label="http"), Port(label="rpc")])]
        job.constraints = [Constraint(l_target="${attr.kernel.name}",
                                      r_target="linux", operand="=")]
        tg.constraints = [Constraint(l_target="${attr.cpu.numcores}",
                                     r_target="2", operand=">=")]
    elif tier == 4:
        # C2M shape: affinity/anti-affinity/spread mixes
        job.affinities = [Affinity(l_target="${node.datacenter}",
                                   r_target="dc1", operand="=",
                                   weight=rng.choice([50, 100]))]
        tg.spreads = [Spread(attribute="${meta.platform.rack}", weight=50)]
    return job


def run_tier_placements(tier: int, n_nodes: int, count: int, seed: int,
                        alg: str, spread_variant: bool = False,
                        with_evictions: bool = False):
    """Build one world, schedule one tier-shaped eval with the given
    algorithm, return {alloc name -> node id} (plus, with_evictions,
    {alloc name -> sorted evicted alloc names})."""
    from .scheduler import Harness

    rng = random.Random(seed)
    mock._counter = itertools.count()
    h = Harness()
    cfg = SchedulerConfiguration(scheduler_algorithm=alg)
    if tier == 5:
        cfg.preemption_config = PreemptionConfig(
            service_scheduler_enabled=True, batch_scheduler_enabled=True)
    h.state.set_scheduler_config(cfg)
    nodes = make_fleet(rng, h, n_nodes, gpus=(tier == 5))
    if tier == 5:
        seed_utilization(rng, h, nodes, 0.95, priorities=(10, 20, 30, 40))
    elif tier in (3, 4):
        seed_utilization(rng, h, nodes, 0.25)

    job = tier_job(tier, rng, count)
    job.id = f"tier{tier}-job-{seed}"
    if tier == 5:
        job.priority = 70
        job.task_groups[0].tasks[0].resources.cpu = 1000
        # BASELINE tier 5: "priority tiers + GPU device reservations".
        # The GPU ask constrains placement to the equipped half of the
        # fleet; preemption pressure stays cpu (the filler jobs hold no
        # devices, so device availability never changes under eviction
        # and the windowed preempt kernel stays exact)
        job.task_groups[0].tasks[0].resources.devices = [
            DeviceRequest(name="nvidia/gpu", count=1)]
    h.state.upsert_job(job)
    ev = mock.evaluation(job_id=job.id, type=job.type,
                         priority=job.priority)
    ev.id = f"tier{tier}-eval-{seed:08d}"
    err = h.process(job.type if job.type in ("service", "batch")
                    else "service", ev)
    assert err is None, err
    placed: Dict[str, str] = {}
    evicted: Dict[str, List[str]] = {}
    for plan in h.plans:
        pre_by_id: Dict[str, List[str]] = {}
        for node_id, allocs in plan.node_preemptions.items():
            for a in allocs:
                pre_by_id.setdefault(a.preempted_by_allocation,
                                     []).append(a.name)
        for node_id, allocs in plan.node_allocation.items():
            for a in allocs:
                if a.eval_id == ev.id:
                    placed[a.name] = node_id
                    evicted[a.name] = sorted(pre_by_id.get(a.id, []))
    if with_evictions:
        return placed, evicted
    return placed


def run_tier_parity(tier: int, n_nodes: int, count: int, seed: int,
                    spread_variant: bool = False
                    ) -> Tuple[Dict[str, str], Dict[str, str]]:
    """host-oracle vs tpu placements for one tier world; caller asserts
    equality."""
    host_alg = "spread" if spread_variant else "binpack"
    tpu_alg = "tpu-spread" if spread_variant else "tpu-binpack"
    host = run_tier_placements(tier, n_nodes, count, seed, host_alg,
                               spread_variant)
    tpu = run_tier_placements(tier, n_nodes, count, seed, tpu_alg,
                              spread_variant)
    return host, tpu
