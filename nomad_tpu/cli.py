"""Operator CLI: `python -m nomad_tpu.cli <command> ...`.

Semantic parity with /root/reference/command/ (mitchellh/cli commands,
main.go:26): job run/plan/status/stop/inspect, node status/drain/
eligibility, alloc status, eval list/status, deployment list/status,
operator scheduler get-config/set-config, server members, system gc,
agent -dev. Talks to the HTTP API through nomad_tpu.api.client.ApiClient,
exactly as the reference CLI rides its api/ module.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from .api.client import ApiClient, ApiError


def _fmt_table(rows: List[List[str]], headers: List[str]) -> str:
    cols = [headers] + [[str(c) for c in r] for r in rows]
    widths = [max(len(r[i]) for r in cols) for i in range(len(headers))]
    lines = ["  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        lines.append("  ".join(str(c).ljust(w)
                               for c, w in zip(r, widths)))
    return "\n".join(lines)


def _client(args) -> ApiClient:
    addr = args.address or os.environ.get("NOMAD_ADDR",
                                          "http://127.0.0.1:4646")
    return ApiClient(addr, namespace=args.namespace,
                     token=os.environ.get("NOMAD_TOKEN", ""))


def _parse_vars(pairs: List[str]) -> dict:
    out = {}
    for p in pairs or []:
        if "=" not in p:
            raise SystemExit(f"bad -var {p!r}, want key=value")
        k, v = p.split("=", 1)
        out[k] = v
    return out


# ---------------------------------------------------------------------------
def cmd_agent(args) -> int:
    from .api.devagent import main as devagent_main
    argv = ["--nodes", str(args.nodes), "--port", str(args.port),
            "--workers", str(args.workers)]
    if args.tpu:
        argv.append("--tpu")
    return devagent_main(argv)


def cmd_job_run(args) -> int:
    api = _client(args)
    variables = _parse_vars(args.var)
    path = args.file
    with open(path, encoding="utf-8") as fh:
        src = fh.read()
    if path.endswith(".json"):
        reply = api.register_job(json.loads(src))
    else:
        reply = api.register_job_hcl(src, variables)
    print(f"==> Evaluation {reply.get('eval_id', '')!r} submitted")
    return 0


def cmd_job_plan(args) -> int:
    api = _client(args)
    with open(args.file, encoding="utf-8") as fh:
        src = fh.read()
    if args.file.endswith(".json"):
        job = json.loads(src)
        job = job.get("job", job)       # accept the wrapped shape too
        job_id = str(job.get("id", ""))
        if not job_id:
            print("Error: job spec has no 'id'", file=sys.stderr)
            return 1
        reply = api.plan_job(job_id, job=job)
    else:
        # send the HCL itself: the server parses it with the full jobspec
        # mapper (devices/spreads/volumes survive; the JSON round-trip
        # through job_from_json is lossier)
        job = api.parse_job(src, _parse_vars(args.var))
        job_id = job["id"]
        reply = api.plan_job(job_id, hcl=src,
                             variables=_parse_vars(args.var))
    print(f"+/- Job: {job_id!r} ({reply.get('diff_type')})")
    print(f"    placed: {reply.get('placed')}  "
          f"stopped: {reply.get('stopped')}")
    failed = reply.get("failed_tg_allocs") or {}
    for tg, metric in failed.items():
        print(f"    WARNING: group {tg!r} would fail placement: "
              f"{metric.get('nodes_evaluated', 0)} nodes evaluated, "
              f"{metric.get('nodes_filtered', 0)} filtered, "
              f"exhausted: {metric.get('dimension_exhausted', {})}")
    for tg, counts in (reply.get("annotations") or {}).get(
            "desired_tg_updates", {}).items():
        shown = {k: v for k, v in counts.items() if v}
        print(f"    group {tg!r}: {shown}")
    print(f"    job modify index: {reply.get('job_modify_index')}")
    return 1 if failed else 0


def cmd_job_status(args) -> int:
    api = _client(args)
    if not args.id:
        jobs = api.jobs()
        print(_fmt_table(
            [[j["id"], j["type"], str(j["priority"]), j["status"]]
             for j in jobs],
            ["ID", "Type", "Priority", "Status"]))
        return 0
    job = api.job(args.id)
    print(f"ID            = {job['id']}")
    print(f"Name          = {job['name']}")
    print(f"Type          = {job['type']}")
    print(f"Priority      = {job['priority']}")
    print(f"Status        = {job['status']}")
    print(f"Version       = {job['version']}")
    allocs = api.job_allocations(args.id)
    if allocs:
        print("\nAllocations")
        print(_fmt_table(
            [[a["id"][:8], a["task_group"], a["node_id"][:8],
              a["desired_status"], a["client_status"]] for a in allocs],
            ["ID", "Task Group", "Node", "Desired", "Status"]))
    return 0


def cmd_job_stop(args) -> int:
    api = _client(args)
    reply = api.deregister_job(args.id, purge=args.purge)
    print(f"==> Evaluation {reply.get('eval_id', '')!r} submitted")
    return 0


def cmd_job_inspect(args) -> int:
    print(json.dumps(_client(args).job(args.id), indent=2, default=str))
    return 0


def cmd_job_history(args) -> int:
    reply = _client(args).job_versions(args.id)
    rows = [[str(v["version"]), "true" if v.get("stable") else "false",
             v.get("status", "")] for v in reply.get("versions", [])]
    print(_fmt_table(rows, ["Version", "Stable", "Status"]))
    return 0


def cmd_job_revert(args) -> int:
    reply = _client(args).revert_job(args.id, args.version)
    print(f"==> Evaluation {reply.get('eval_id', '')!r} submitted")
    return 0


def cmd_job_dispatch(args) -> int:
    payload = b""
    if args.payload_file:
        with open(args.payload_file, "rb") as f:
            payload = f.read()
    meta = dict(kv.split("=", 1) for kv in (args.meta or []))
    reply = _client(args).dispatch_job(args.id, payload, meta,
                                       args.idempotency_token)
    print(f"Dispatched Job ID = {reply.get('dispatched_job_id', '')}")
    print(f"Evaluation ID     = {reply.get('eval_id', '')}")
    return 0


def cmd_job_scale(args) -> int:
    reply = _client(args).scale_job(args.id, args.group, args.count,
                                    message=args.message)
    print(f"==> Evaluation {reply.get('eval_id', '')!r} submitted")
    return 0


def cmd_node_status(args) -> int:
    api = _client(args)
    if not args.id:
        nodes = api.nodes()
        print(_fmt_table(
            [[n["id"][:8], n["name"], n["datacenter"], n["node_class"],
              "true" if n["drain"] else "false",
              n["scheduling_eligibility"], n["status"]] for n in nodes],
            ["ID", "Name", "DC", "Class", "Drain", "Eligibility",
             "Status"]))
        return 0
    n = api.node(args.id)
    print(json.dumps(n, indent=2, default=str))
    return 0


def cmd_node_drain(args) -> int:
    api = _client(args)
    api.drain_node(args.id, enable=args.enable,
                   deadline_s=args.deadline)
    print(f"Node {args.id!r} drain "
          f"{'enabled' if args.enable else 'disabled'}")
    return 0


def cmd_node_eligibility(args) -> int:
    api = _client(args)
    api.node_eligibility(args.id, eligible=args.enable)
    print(f"Node {args.id!r} marked "
          f"{'eligible' if args.enable else 'ineligible'}")
    return 0


def cmd_alloc_status(args) -> int:
    a = _client(args).allocation(args.id)
    print(f"ID         = {a['id']}")
    print(f"Name       = {a['name']}")
    print(f"Node       = {a['node_id']}")
    print(f"Job        = {a['job_id']}")
    print(f"Desired    = {a['desired_status']}")
    print(f"Status     = {a['client_status']}")
    metrics = a.get("metrics") or {}
    scores = metrics.get("scores") or {}
    if scores:
        print("\nPlacement Metrics")
        for key, score in sorted(scores.items())[:8]:
            print(f"  {key} = {score:.4f}"
                  if isinstance(score, float) else f"  {key} = {score}")
    return 0


def cmd_alloc_stop(args) -> int:
    """(reference: command/alloc_stop.go)"""
    out = _client(args).post(f"/v1/allocation/{args.id}/stop")
    print(f"Stop requested; follow-up eval {out.get('eval_id')}")
    return 0


def cmd_alloc_signal(args) -> int:
    """(reference: command/alloc_signal.go)"""
    out = _client(args).post(
        f"/v1/client/allocation/{args.id}/signal",
        {"task": args.task, "signal": args.signal})
    print(f"Signalled {out.get('signalled')} with {out.get('signal')}")
    return 0


def cmd_alloc_restart(args) -> int:
    """(reference: command/alloc_restart.go)"""
    out = _client(args).post(
        f"/v1/client/allocation/{args.id}/restart",
        {"task": args.task or ""})
    print(f"Restarted: {', '.join(out.get('restarted', []))}")
    return 0


def cmd_alloc_exec(args) -> int:
    """(reference: command/alloc_exec.go, non-interactive form)"""
    out = _client(args).request(
        "POST", f"/v1/client/allocation/{args.id}/exec",
        body={"task": args.task, "cmd": args.cmd,
              "timeout": args.timeout},
        timeout=args.timeout + 10.0)    # pad past every server-side leg
    sys.stdout.write(out.get("stdout", ""))
    sys.stderr.write(out.get("stderr", ""))
    return int(out.get("exit_code", 0))


def cmd_alloc_fs(args) -> int:
    api = _client(args)
    path = args.path or "/"
    st = api.fs_stat(args.id, path)
    if st["is_dir"]:
        entries = api.fs_list(args.id, path)
        print(_fmt_table(
            [[("d" if e["is_dir"] else "-"), str(e["size"]), e["name"]]
             for e in entries],
            ["Mode", "Size", "Name"]))
    else:
        sys.stdout.buffer.write(api.fs_cat(args.id, path))
    return 0


def cmd_alloc_logs(args) -> int:
    # -tail N rides the fs tail semantics (negative offset = last N
    # bytes across rotated frames, reference origin="end"); the read
    # limit must widen with N or fs_logs' 1 MiB default would return a
    # middle slice for large tails. -n LINES gives the reference CLI's
    # line semantics (ADVICE low #3): over-fetch a byte window from the
    # end, keep only the last LINES lines.
    if args.tail < 0:
        print("-tail must be a positive byte count", file=sys.stderr)
        return 1
    if args.lines < 0:
        print("-n must be a positive line count", file=sys.stderr)
        return 1
    api = _client(args)
    log_type = "stderr" if args.stderr else "stdout"
    offset = -args.tail if args.tail else 0
    if args.lines:
        fetch = args.tail or max(1 << 16, args.lines * 1024)
        data = api.alloc_logs(args.id, args.task, log_type,
                              offset=-fetch, limit=fetch)
        lines = data.splitlines(keepends=True)[-args.lines:]
        if not args.f:
            sys.stdout.buffer.write(b"".join(lines))
            sys.stdout.buffer.flush()
            return 0
        # follow starting at the last LINES lines (reference
        # `-tail -n N -f`): resume the stream that many bytes back
        offset = -sum(len(ln) for ln in lines)
    if args.f:
        # follow: chunked stream, printed as it arrives (reference:
        # alloc logs -f); urllib decodes the chunked framing
        import urllib.request
        url = api._url(f"/v1/client/fs/logs/{args.id}/{args.task}",
                       {"type": log_type, "offset": str(offset),
                        "follow": "true"})
        req = urllib.request.Request(url, headers=api._headers())
        try:
            with urllib.request.urlopen(req,
                                        context=api._ssl_ctx) as resp:
                while True:
                    # read1: return WHATEVER is available (read(n)
                    # would block until n bytes buffer -- a tail must
                    # print lines as they arrive)
                    block = resp.read1(8192)
                    if not block:
                        break
                    sys.stdout.buffer.write(block)
                    sys.stdout.buffer.flush()
        except KeyboardInterrupt:
            pass
        return 0
    kwargs = {"offset": offset}
    if args.tail:
        kwargs["limit"] = args.tail
    data = api.alloc_logs(args.id, args.task, log_type, **kwargs)
    sys.stdout.buffer.write(data)
    return 0


def cmd_node_purge(args) -> int:
    """(reference: command/node_purge.go)"""
    _client(args).post(f"/v1/node/{args.id}/purge")
    print(f"Purged node {args.id}")
    return 0


def cmd_node_stats(args) -> int:
    stats = _client(args).client_stats(args.id)
    print(json.dumps(stats, indent=2))
    return 0


def cmd_eval(args) -> int:
    api = _client(args)
    if args.id:
        print(json.dumps(api.evaluation(args.id), indent=2, default=str))
    else:
        evals = api.evaluations()
        print(_fmt_table(
            [[e["id"][:8], e["priority"], e["triggered_by"], e["job_id"],
              e["status"]] for e in evals],
            ["ID", "Priority", "Triggered By", "Job ID", "Status"]))
    return 0


def cmd_deployment_op(args) -> int:
    """(reference: command/deployment_{promote,pause,resume,fail}.go)"""
    api = _client(args)
    if args.sub == "promote":
        body = {"groups": args.group} if args.group else None
        api.post(f"/v1/deployment/promote/{args.id}", body)
        print(f"Promoted deployment {args.id}"
              + (f" (groups: {', '.join(args.group)})" if args.group
                 else ""))
    elif args.sub == "pause":
        api.post(f"/v1/deployment/pause/{args.id}", {"pause": True})
        print(f"Paused deployment {args.id}")
    elif args.sub == "resume":
        api.post(f"/v1/deployment/pause/{args.id}", {"pause": False})
        print(f"Resumed deployment {args.id}")
    else:
        api.post(f"/v1/deployment/fail/{args.id}")
        print(f"Failed deployment {args.id}")
    return 0


def cmd_deployment(args) -> int:
    api = _client(args)
    deps = api.deployments()
    print(_fmt_table(
        [[d["id"][:8], d["job_id"], str(d["job_version"]), d["status"],
          d["status_description"]] for d in deps],
        ["ID", "Job ID", "Version", "Status", "Description"]))
    return 0


def cmd_operator_scheduler(args) -> int:
    api = _client(args)
    if args.algorithm:
        api.set_scheduler_config(scheduler_algorithm=args.algorithm,
                                 memory_oversubscription_enabled=args.memory_oversub)
        print(f"Scheduler algorithm set to {args.algorithm!r}")
    cfg = api.scheduler_config()
    print(json.dumps(cfg, indent=2, default=str))
    return 0


def cmd_server_members(args) -> int:
    reply = _client(args).members()
    print(_fmt_table(
        [[m["name"], f"{m['addr'][0]}:{m['addr'][1]}"
          if isinstance(m.get("addr"), list) else "-",
          m["status"]] for m in reply.get("members", [])],
        ["Name", "Address", "Status"]))
    return 0


def cmd_system_gc(args) -> int:
    print(json.dumps(_client(args).system_gc()))
    return 0


def cmd_metrics(args) -> int:
    print(json.dumps(_client(args).metrics(), indent=2, default=str))
    return 0


def cmd_var_put(args) -> int:
    api = _client(args)
    items = _parse_vars(args.items)
    params = {}
    if args.cas is not None:
        params["cas"] = args.cas
    out = api.request("PUT", f"/v1/var/{args.path}", body={"items": items},
                      params=params)
    print(f"Wrote {args.path} @ index "
          f"{out.get('meta', {}).get('modify_index')}")
    return 0


def cmd_var_get(args) -> int:
    out = _client(args).get(f"/v1/var/{args.path}")
    print(json.dumps(out, indent=2, default=str))
    return 0


def cmd_var_list(args) -> int:
    out = _client(args).get("/v1/vars", prefix=args.prefix or "")
    print(_fmt_table([[m["namespace"], m["path"], m["modify_index"]]
                      for m in out],
                     ["Namespace", "Path", "Index"]))
    return 0


def cmd_var_purge(args) -> int:
    params = {}
    if args.cas is not None:
        params["cas"] = args.cas
    _client(args).request("DELETE", f"/v1/var/{args.path}", params=params)
    print(f"Purged {args.path}")
    return 0


def cmd_operator_keyring(args) -> int:
    api = _client(args)
    if args.sub2 == "rotate":
        out = api.post("/v1/operator/keyring/rotate")
        print(f"Rotated root key -> {out['key_id']}")
        return 0
    keys = api.get("/v1/operator/keyring/keys")
    print(_fmt_table([[k["key_id"], k["state"]] for k in keys],
                     ["Key ID", "State"]))
    return 0


def cmd_operator_raft(args) -> int:
    """(reference: command/operator_raft_*.go)"""
    api = _client(args)
    if args.sub2 == "remove-peer":
        api.post("/v1/operator/raft/remove-peer", {"id": args.id})
        print(f"Removed raft peer {args.id}")
        return 0
    cfg = api.get("/v1/operator/raft/configuration")
    print(_fmt_table(
        [[s["id"], s["address"], "leader" if s["leader"] else "follower",
          "true" if s["voter"] else "false"] for s in cfg["servers"]],
        ["ID", "Address", "State", "Voter"]))
    return 0


def cmd_acl_bootstrap(args) -> int:
    out = _client(args).post("/v1/acl/bootstrap")
    print(f"Accessor ID = {out['accessor_id']}\n"
          f"Secret ID   = {out['secret_id']}\n"
          f"Type        = {out['type']}")
    return 0


def cmd_acl_policy_apply(args) -> int:
    with open(args.file, encoding="utf-8") as fh:
        rules = fh.read()
    _client(args).post(f"/v1/acl/policy/{args.name}",
                       body={"rules": rules,
                             "description": args.description or ""})
    print(f"Applied policy {args.name}")
    return 0


def cmd_acl_token_create(args) -> int:
    out = _client(args).post(
        "/v1/acl/token",
        body={"name": args.name or "", "type": args.type,
              "policies": args.policy or [],
              "roles": args.role or []})
    print(f"Accessor ID = {out['accessor_id']}\n"
          f"Secret ID   = {out['secret_id']}\n"
          f"Policies    = {out['policies']}\n"
          f"Roles       = {out.get('roles', [])}")
    return 0


def cmd_acl_role(args) -> int:
    """(reference: command/acl_role_*.go)"""
    api = _client(args)
    if args.sub2 == "apply":
        api.post(f"/v1/acl/role/{args.name}",
                 {"policies": args.policy or [],
                  "description": args.description or ""})
        print(f"Applied role {args.name}")
    elif args.sub2 == "delete":
        api.request("DELETE", f"/v1/acl/role/{args.name}")
        print(f"Deleted role {args.name}")
    else:
        roles = api.get("/v1/acl/roles")
        print(_fmt_table(
            [[r["name"], ", ".join(r["policies"]),
              r.get("description", "")] for r in roles],
            ["Name", "Policies", "Description"]))
    return 0


def cmd_namespace(args) -> int:
    api = _client(args)
    if args.sub2 == "list":
        print(_fmt_table([[n["name"], n.get("description", "")]
                          for n in api.namespaces()],
                         ["Name", "Description"]))
    elif args.sub2 == "apply":
        api.upsert_namespace(args.name, description=args.description)
        print(f"Namespace {args.name!r} applied")
    elif args.sub2 == "delete":
        api.delete_namespace(args.name)
        print(f"Namespace {args.name!r} deleted")
    return 0


def cmd_node_pool(args) -> int:
    api = _client(args)
    if args.sub2 == "list":
        print(_fmt_table(
            [[p["name"], p.get("scheduler_algorithm") or "(global)",
              p.get("description", "")]
             for p in api.node_pools()],
            ["Name", "SchedulerAlgorithm", "Description"]))
    elif args.sub2 == "apply":
        api.upsert_node_pool(args.name, description=args.description,
                             scheduler_algorithm=args.scheduler_algorithm)
        print(f"Node pool {args.name!r} applied")
    elif args.sub2 == "delete":
        api.delete_node_pool(args.name)
        print(f"Node pool {args.name!r} deleted")
    elif args.sub2 == "nodes":
        print(_fmt_table(
            [[n["id"][:8], n["name"], n["status"]]
             for n in api.node_pool_nodes(args.name)],
            ["ID", "Name", "Status"]))
    return 0


def cmd_monitor(args) -> int:
    """Stream agent logs (reference: command/monitor.go riding
    /v1/agent/monitor). Ctrl-C detaches."""
    import urllib.request
    api = _client(args)
    url = (f"{api.address}/v1/agent/monitor?plain=true"
           f"&log_level={args.log_level}")
    req = urllib.request.Request(url, headers=api._headers())
    try:
        with urllib.request.urlopen(req, context=api._ssl_ctx) as resp:
            for raw in resp:
                line = raw.decode(errors="replace").rstrip("\n")
                if line:
                    print(line, flush=True)
    except KeyboardInterrupt:
        pass
    return 0


def cmd_operator_debug(args) -> int:
    """Capture a debug bundle (reference: command/operator_debug.go):
    agent/cluster/scheduler state, thread stacks, metrics, guard state,
    recent evals/deployments, and a log capture, tarred for transport."""
    import io
    import json as _json
    import tarfile
    import threading
    import time as _time
    import urllib.request

    api = _client(args)
    stamp = _time.strftime("%Y%m%d-%H%M%S")
    out_path = args.output or f"nomad-tpu-debug-{stamp}.tar.gz"
    captures = {}

    def grab(name: str, path: str) -> None:
        try:
            captures[name] = api.get(path)
        except Exception as e:  # noqa: BLE001 -- partial bundles beat none
            captures[name] = {"capture_error": repr(e)}

    # log capture rides the monitor stream for the requested duration;
    # runs first in a thread so the state grabs land inside the window
    log_lines: list = []

    def capture_logs() -> None:
        url = (f"{api.address}/v1/agent/monitor?plain=true"
               f"&log_level=debug")
        req = urllib.request.Request(url, headers=api._headers())
        deadline = _time.time() + args.duration
        # socket timeout must outlive the server's 10s heartbeat frame,
        # or a quiet agent makes every capture "fail" on timeout; a
        # timeout after the window is just a clean end of capture
        try:
            with urllib.request.urlopen(
                    req, timeout=max(args.duration, 12.0),
                    context=api._ssl_ctx) as resp:
                while _time.time() < deadline:
                    line = resp.readline()
                    if not line:
                        break
                    log_lines.append(line.decode(errors="replace"))
        except TimeoutError:
            pass
        except Exception as e:  # noqa: BLE001
            log_lines.append(f"[capture error: {e!r}]\n")

    t = threading.Thread(target=capture_logs, daemon=True)
    t.start()

    grab("agent-self.json", "/v1/agent/self")
    grab("agent-members.json", "/v1/agent/members")
    grab("agent-health.json", "/v1/agent/health")
    grab("threads.json", "/v1/agent/pprof/goroutine")
    grab("metrics.json", "/v1/metrics")
    try:
        captures["traces.json"] = api.get("/v1/agent/trace", slowest=10)
    except Exception as e:  # noqa: BLE001 -- partial bundles beat none
        captures["traces.json"] = {"capture_error": repr(e)}
    grab("scheduler-config.json", "/v1/operator/scheduler/configuration")
    # quality scoreboard + shadow-audit + saturation attribution next
    # to the metrics.json snapshot it contextualizes (ISSUE 7)
    grab("quality.json", "/v1/operator/quality")
    # lock-order sanitizer findings as their own bundle member: the
    # deadlock-witness stacks belong next to threads.json when an
    # operator is untangling a wedge (ISSUE 9)
    try:
        captures["lockcheck.json"] = (
            captures["agent-self.json"]["stats"]["lockcheck"])
    except Exception as e:  # noqa: BLE001 -- partial bundles beat none
        captures["lockcheck.json"] = {"capture_error": repr(e)}
    # dispatch-discipline sanitizer findings as their own member: the
    # retrace/host-sync witnesses belong next to traces.json when an
    # operator is untangling a slow TPU path (ISSUE 10)
    try:
        captures["jitcheck.json"] = (
            captures["agent-self.json"]["stats"]["jitcheck"])
    except Exception as e:  # noqa: BLE001 -- partial bundles beat none
        captures["jitcheck.json"] = {"capture_error": repr(e)}
    # snapshot-isolation sanitizer findings as their own member: the
    # torn-read/aliasing witnesses belong next to lockcheck.json when
    # an operator is untangling a cross-worker state corruption
    # (ISSUE 11)
    try:
        captures["statecheck.json"] = (
            captures["agent-self.json"]["stats"]["statecheck"])
    except Exception as e:  # noqa: BLE001 -- partial bundles beat none
        captures["statecheck.json"] = {"capture_error": repr(e)}
    # deterministic-schedule explorer findings as their own member:
    # the deadlock/divergence counterexamples (seed + decision trace)
    # belong next to lockcheck.json when an operator is replaying a
    # concurrency wedge (ISSUE 12)
    try:
        captures["schedcheck.json"] = (
            captures["agent-self.json"]["stats"]["schedcheck"])
    except Exception as e:  # noqa: BLE001 -- partial bundles beat none
        captures["schedcheck.json"] = {"capture_error": repr(e)}
    # sharding-discipline sanitizer findings as their own member: the
    # spec-drift/implicit-transfer witnesses and the per-program
    # collective inventory belong next to jitcheck.json when an
    # operator is untangling a slow or bloated mesh path (ISSUE 15)
    try:
        captures["shardcheck.json"] = (
            captures["agent-self.json"]["stats"]["shardcheck"])
    except Exception as e:  # noqa: BLE001 -- partial bundles beat none
        captures["shardcheck.json"] = {"capture_error": repr(e)}
    # transfer ledger + residency map + tunnel fit as their own member:
    # the byte decomposition belongs next to metrics.json when an
    # operator is untangling a slow or bloated dispatch path (ISSUE 13)
    try:
        captures["xferobs.json"] = (
            captures["agent-self.json"]["stats"]["xferobs"])
    except Exception as e:  # noqa: BLE001 -- partial bundles beat none
        captures["xferobs.json"] = {"capture_error": repr(e)}
    grab("autopilot-health.json", "/v1/operator/autopilot/health")
    grab("nodes.json", "/v1/nodes")
    grab("jobs.json", "/v1/jobs")
    grab("evaluations.json", "/v1/evaluations")
    grab("deployments.json", "/v1/deployments")
    # daemon thread: if it is still blocked waiting for a first frame
    # from a quiet agent, take what arrived and move on
    t.join(timeout=args.duration + 2)
    captures["monitor.log"] = "".join(log_lines)

    with tarfile.open(out_path, "w:gz") as tar:
        for name, content in captures.items():
            if isinstance(content, str):
                blob = content.encode()
            else:
                blob = _json.dumps(content, indent=2,
                                   default=str).encode()
            info = tarfile.TarInfo(f"nomad-tpu-debug-{stamp}/{name}")
            info.size = len(blob)
            info.mtime = int(_time.time())
            tar.addfile(info, io.BytesIO(blob))
    print(f"Debug bundle written to {out_path} "
          f"({len(captures)} captures, {len(log_lines)} log lines)")
    return 0


def cmd_operator_solver(args) -> int:
    """Accelerator guard state / re-probe (rides /v1/agent/self and
    POST /v1/operator/solver/reprobe)."""
    api = _client(args)
    if args.sub2 == "status":
        st = api.get("/v1/agent/self")["stats"]["solver_guard"]
        for k in ("checked", "ok", "degraded", "probe_timed_out",
                  "recovered_late", "host_fallback_dispatches",
                  "backend_unavailable_total", "recovered_total"):
            print(f"{k:28s} = {st.get(k)}")
        br = st.get("breaker") or {}
        for k in ("state", "consecutive_failures", "trips",
                  "recoveries", "backoff_s"):
            print(f"breaker.{k:20s} = {br.get(k)}")
        dis = st.get("dispatch") or {}
        for k in ("ok", "timeout", "error", "bytes_total"):
            print(f"dispatch.{k:19s} = {dis.get(k)}")
        pipe = st.get("dispatch_pipeline") or {}
        for k in ("depth", "in_flight"):
            print(f"pipeline.{k:19s} = {pipe.get(k)}")
        me = st.get("mesh") or {}
        for k in ("enabled", "devices", "grid", "dispatches",
                  "lpq_dispatches"):
            print(f"mesh.{k:23s} = {me.get(k)}")
        cc = st.get("const_cache") or {}
        for k in ("enabled", "entries", "resident_bytes", "hits",
                  "misses", "bytes_saved_total", "invalidations",
                  "shard_entries", "shard_resident_bytes"):
            print(f"const_cache.{k:16s} = {cc.get(k)}")
        pc = st.get("pack_cache") or {}
        for k in ("enabled", "hits", "misses", "matrix_hits",
                  "matrix_misses", "usage_base_hits",
                  "usage_base_misses", "invalidations"):
            print(f"pack_cache.{k:17s} = {pc.get(k)}")
        ar = st.get("pack_arena") or {}
        for k in ("enabled", "entries", "in_use", "resident_bytes",
                  "reuses", "allocs", "evictions", "pad_fills_skipped"):
            print(f"pack_arena.{k:17s} = {ar.get(k)}")
        pk = st.get("pack") or {}
        ms = pk.get("ms") or {}
        print(f"pack.p50_ms              = {ms.get('p50_ms')}")
        print(f"pack.cache_hit           = {pk.get('cache_hit')}")
        print(f"pack.cache_miss          = {pk.get('cache_miss')}")
    elif args.sub2 == "reprobe":
        # a first-touch reprobe legitimately blocks for the in-process
        # probe deadline (<=30s) plus the subprocess transport probe
        api.timeout = 150.0
        rep = api.post("/v1/operator/solver/reprobe")
        print(f"recovered          = {rep.get('recovered')}")
        if rep.get("subprocess") is not None:
            sub = rep["subprocess"]
            print(f"transport probe    = "
                  f"{'TIMED OUT' if sub['timed_out'] else 'ok'} "
                  f"(devices={sub['devices']})")
        if rep.get("tunnel_ok_process_wedged"):
            print("verdict            = transport healthy but this "
                  "process is wedged: restart the agent to recover")
        print(f"guard ok           = {rep['state']['ok']}")
    return 0


def cmd_operator_node_flaps(args) -> int:
    """Flap-damping state (rides /v1/agent/self stats.node_flaps): per-
    node flap scores in the scoring window plus active quarantines --
    the `operator solver status` analog for the node lifecycle layer."""
    api = _client(args)
    st = api.get("/v1/agent/self")["stats"].get("node_flaps") or {}
    for k in ("enabled", "threshold", "window_s", "base_s", "max_s"):
        print(f"{k:12s} = {st.get(k)}")
    scores = st.get("scores") or {}
    quarantined = st.get("quarantined") or {}
    print(f"flapping     = {len(scores)} node(s)")
    for nid, score in sorted(scores.items(), key=lambda kv: -kv[1]):
        q = quarantined.get(nid)
        print(f"  {nid:38s} score={score:<4d}"
              + (f" quarantined {q:.1f}s" if q is not None else ""))
    for nid, rem in sorted(quarantined.items()):
        if nid not in scores:
            print(f"  {nid:38s} score=0    quarantined {rem:.1f}s")
    return 0


def cmd_operator_workers(args) -> int:
    """Supervised worker pool state (rides /v1/agent/self
    stats.worker_pool): per-slot liveness + progress-heartbeat age,
    and the supervisor's death/wedge/restart counters (ISSUE 16)."""
    api = _client(args)
    st = api.get("/v1/agent/self")["stats"].get("worker_pool") or {}
    for k in ("enabled", "stall_s", "restart_base_s", "restart_max_s",
              "restarts_total", "deaths_detected", "wedges_detected",
              "pending_restarts"):
        print(f"{k:16s} = {st.get(k)}")
    workers = st.get("workers") or []
    print(f"workers          = {len(workers)}")
    for w in workers:
        print(f"  {w['name']:28s} alive={str(w['alive']).lower():5s} "
              f"evals={w['evals_processed']:<8d} "
              f"progress_age={w['progress_age_s']:.1f}s")
    return 0


def cmd_operator_evals_quarantine(args) -> int:
    """Poison-eval dead-letter set (rides /v1/agent/self
    stats.eval_quarantine): evals that exhausted their delivery limit
    NOMAD_TPU_POISON_AFTER times and were pulled from the retry loop.
    --release <id> / --release-all re-admit with a clean slate once
    the root cause is fixed (ISSUE 16)."""
    api = _client(args)
    if getattr(args, "release", None) or getattr(args, "release_all",
                                                 False):
        body = ({"release_all": True} if args.release_all
                else {"eval_id": args.release})
        out = api.post("/v1/operator/quarantine", body)
        released = out.get("released") or []
        print(f"released {len(released)} eval(s)")
        for eid in released:
            print(f"  {eid}")
        st = out.get("quarantine") or {}
    else:
        st = api.get("/v1/agent/self")["stats"].get(
            "eval_quarantine") or {}
    for k in ("poison_after", "delivery_limit", "total"):
        print(f"{k:14s} = {st.get(k)}")
    for rec in st.get("evals") or []:
        print(f"  {rec['id']:34s} job={rec['job_id']:20s} "
              f"type={rec['type']:8s} strikes={rec['strikes']:<3d} "
              f"age={rec['age_s']:.1f}s trigger={rec['triggered_by']}")
    return 0


def cmd_operator_lockcheck(args) -> int:
    """Lock-order sanitizer report (rides /v1/agent/self
    stats.lockcheck): acquisition-order cycles with both witness
    stacks, locks held across dispatch/fault-point/blocking waits, and
    escaped-frame bare acquires. Enable with NOMAD_TPU_LOCKCHECK=1 on
    the agent; off is a true no-op and reports enabled=False."""
    api = _client(args)
    st = api.get("/v1/agent/self")["stats"].get("lockcheck") or {}
    for k in ("enabled", "wait_ms", "locks", "acquires", "edges",
              "edges_dropped", "reports_dropped", "cycle_count"):
        print(f"{k:15s} = {st.get(k)}")
    if not st.get("enabled") and not st.get("cycle_count"):
        print("(checker disabled: set NOMAD_TPU_LOCKCHECK=1 on the "
              "agent to record lock orders)")
    for i, cyc in enumerate(st.get("cycles") or []):
        print(f"\nCYCLE {i}: potential deadlock over "
              f"{' -> '.join(cyc.get('locks') or [])}")
        for e in cyc.get("edges") or []:
            print(f"  edge {e.get('from')} -> {e.get('to')} "
                  f"[thread {e.get('thread')}]")
            if args.stacks:
                for ln in (e.get("stack") or "").rstrip().splitlines():
                    print(f"    {ln}")
    ha = st.get("held_across") or []
    if ha:
        print(f"\nheld-across violations: {len(ha)}")
        for v in ha:
            held = ", ".join(h.get("lock", "?")
                             for h in v.get("held") or [])
            det = f" ({v['detail']})" if v.get("detail") else ""
            print(f"  {v.get('kind')}{det} holding [{held}] "
                  f"[thread {v.get('thread')}]")
            if args.stacks:
                for ln in (v.get("stack") or "").rstrip().splitlines():
                    print(f"    {ln}")
    esc = st.get("escaped") or []
    if esc:
        print(f"\nescaped-frame bare acquires: {len(esc)}")
        for v in esc:
            print(f"  {v.get('lock')} acquired at "
                  f"{v.get('acquired_at')} in {v.get('in_function')}()"
                  f" [{v.get('reason')}, thread {v.get('thread')}]")
    return 1 if st.get("cycle_count") else 0


def cmd_operator_jitcheck(args) -> int:
    """Dispatch-discipline sanitizer report (rides /v1/agent/self
    stats.jitcheck): steady-state retraces with witness signature
    pairs, hot-path host syncs with span attribution, dtype drift and
    fingerprint-cache mutations. Enable with NOMAD_TPU_JITCHECK=1 on
    the agent; off is a true no-op and reports enabled=False. Exit 1
    when steady-state retraces exist."""
    api = _client(args)
    st = api.get("/v1/agent/self")["stats"].get("jitcheck") or {}
    for k in ("enabled", "warmup", "jits", "calls", "traces",
              "site_count", "retrace_count", "late_trace_count",
              "host_sync_count", "sanctioned_fetches",
              "x64_leak_count", "mutation_count", "reports_dropped"):
        print(f"{k:20s} = {st.get(k)}")
    if not st.get("enabled") and not st.get("retrace_count"):
        print("(checker disabled: set NOMAD_TPU_JITCHECK=1 on the "
              "agent to account traces)")
    if args.sites:
        for s in st.get("sites") or []:
            print(f"  site {s.get('site'):42s} jits={s.get('jits'):<3d}"
                  f" calls={s.get('calls'):<6d}"
                  f" traces={s.get('traces'):<4d}"
                  f" sigs={s.get('sigs'):<4d}"
                  f" steady={s.get('steady')}")
    for i, r in enumerate(st.get("retraces") or []):
        w = r.get("witness") or {}
        print(f"\nRETRACE {i}: {r.get('site')} traced "
              f"{r.get('count')}x for one abstract signature")
        print(f"  new  {r.get('signature')}")
        for old in w.get("old") or []:
            print(f"  old  {old}")
        print(f"  [thread {r.get('thread')}]")
    for r in st.get("late_traces") or []:
        print(f"late trace (report-only): {r.get('site')} "
              f"new sig {r.get('signature')} after steady state")
    for r in st.get("host_syncs") or []:
        print(f"hot-path host sync: {r.get('kind')} at {r.get('site')} "
              f"x{r.get('count')} (dispatch {r.get('label')!r}, "
              f"evals {r.get('evals')})")
    for r in st.get("dtype_drift") or []:
        print(f"dtype drift: {r.get('kind')} at {r.get('site')} "
              f"({r.get('where')}, {r.get('leaves')} leaves)")
    for r in st.get("mutations") or []:
        print(f"cache mutation: {r.get('kind')} at {r.get('site')} -- "
              f"{r.get('detail')}")
    return 1 if st.get("retrace_count") else 0


def cmd_operator_statecheck(args) -> int:
    """MVCC snapshot-isolation sanitizer report (rides /v1/agent/self
    stats.statecheck): torn snapshot reads and aliasing writes with
    witness stacks, delta-journal coverage gaps, write-skew witnesses
    and stale version-keyed memos. Enable with NOMAD_TPU_STATECHECK=1
    on the agent; off is a true no-op and reports enabled=False. Exit
    1 when torn reads or aliasing writes exist."""
    api = _client(args)
    st = api.get("/v1/agent/self")["stats"].get("statecheck") or {}
    for k in ("enabled", "reads", "mutations", "scopes",
              "journal_writes", "batch_commits", "memo_serves",
              "published_arrays", "registered_rows",
              "torn_read_count", "aliasing_write_count",
              "journal_gap_count", "write_skew_count",
              "stale_memo_count", "drift_count", "reports_dropped"):
        print(f"{k:20s} = {st.get(k)}")
    if not st.get("enabled") and not st.get("torn_read_count"):
        print("(checker disabled: set NOMAD_TPU_STATECHECK=1 on the "
              "agent to record store discipline)")
    for i, r in enumerate(st.get("torn_reads") or []):
        print(f"\nTORN READ {i}: {r.get('kind')} in {r.get('op')} at "
              f"{r.get('site')} versions {r.get('versions')} "
              f"(evals {r.get('evals')}, thread {r.get('thread')})")
        if args.stacks:
            for ln in (r.get("stack") or "").rstrip().splitlines():
                print(f"    {ln}")
    for i, r in enumerate(st.get("aliasing_writes") or []):
        print(f"\nALIASING WRITE {i}: {r.get('kind')} at "
              f"{r.get('site')} -- {r.get('detail')} "
              f"[thread {r.get('thread')}]")
        if args.stacks:
            for ln in (r.get("stack") or "").rstrip().splitlines():
                print(f"    {ln}")
    for r in st.get("journal_gaps") or []:
        print(f"journal gap (report-only): delta-less allocs write at "
              f"{r.get('site')} (tables {r.get('tables')})")
    for r in st.get("write_skews") or []:
        print(f"write skew (report-only): node {r.get('node')} touched "
              f"by plans {r.get('plans')} in ONE batch commit")
    for r in st.get("stale_memos") or []:
        print(f"stale memo: {r.get('kind')} at {r.get('site')} entry "
              f"v{r.get('entry_version')} vs live "
              f"v{r.get('live_version')}")
    for r in st.get("drifts") or []:
        print(f"snapshot drift (designed, report-only): {r.get('op')} "
              f"at {r.get('site')} versions {r.get('versions')}")
    return 1 if (st.get("torn_read_count")
                 or st.get("aliasing_write_count")) else 0


def cmd_operator_schedcheck(args) -> int:
    """Deterministic schedule explorer (rides /v1/agent/self
    stats.schedcheck): run/seed/policy state, decision counters, and
    the deadlock/divergence counterexamples.  ``--replay SEED``
    re-runs a built-in scenario under the exact recorded interleaving
    LOCALLY (no agent round-trip) with lockcheck+statecheck armed;
    ``--explore N`` sweeps N seeds.  Exit 1 when violations (or agent
    deadlock reports) exist."""
    from nomad_tpu import schedcheck

    def _print_run(res) -> int:
        print(f"seed         = {res.seed}")
        print(f"policy       = {res.policy}")
        print(f"decisions    = {res.decisions}")
        print(f"fingerprint  = {res.fingerprint}")
        if res.error is not None:
            print(f"error        = {res.error!r}")
        print(f"violations   = {len(res.violations)}")
        for v in res.violations:
            sched = v.get("schedule") or {}
            at = (f" @ step {sched.get('step')}"
                  if sched.get("step") is not None else "")
            detail = " ".join(
                f"{k}={v[k]}" for k in ("op", "site", "node", "plans",
                                        "versions", "locks")
                if v.get(k) is not None)
            print(f"  [{v['checker']}] {v['kind']}{at} {detail}")
        return 1 if res.violations else 0

    if args.replay is not None:
        fn = schedcheck.SCENARIOS.get(args.scenario)
        if fn is None:
            print(f"unknown scenario {args.scenario!r} (have: "
                  f"{', '.join(sorted(schedcheck.SCENARIOS))})")
            return 2
        res = schedcheck.replay(fn, args.replay, policy=args.policy)
        return _print_run(res)
    if args.explore is not None:
        fn = schedcheck.SCENARIOS.get(args.scenario)
        if fn is None:
            print(f"unknown scenario {args.scenario!r} (have: "
                  f"{', '.join(sorted(schedcheck.SCENARIOS))})")
            return 2
        agg = schedcheck.explore(fn, seeds=args.explore,
                                 policy=args.policy)
        print(f"explored     = {len(agg.runs)} schedules "
              f"(scenario {args.scenario})")
        print(f"violations   = {len(agg.violations)} across seeds "
              f"{agg.seeds_with_violations}")
        for r in agg.runs:
            if r.violations:
                print(f"--- seed {r.seed} "
                      f"(replay: operator schedcheck --replay {r.seed} "
                      f"--scenario {args.scenario})")
                _print_run(r)
        return 1 if agg.violations else 0
    api = _client(args)
    st = api.get("/v1/agent/self")["stats"].get("schedcheck") or {}
    for k in ("enabled", "run_active", "seed", "policy", "depth",
              "park_s", "runs", "decisions", "parks", "preemptions",
              "timeout_wakes", "deadlock_count", "divergence_count",
              "threads_managed", "reports_dropped"):
        print(f"{k:16s} = {st.get(k)}")
    if not st.get("enabled") and not st.get("deadlock_count"):
        print("(checker disabled: set NOMAD_TPU_SCHEDCHECK=1 on the "
              "agent to control schedules)")
    lr = st.get("last_run") or {}
    if lr:
        print(f"last run: seed={lr.get('seed')} "
              f"policy={lr.get('policy')} "
              f"decisions={lr.get('decisions')} "
              f"fingerprint={lr.get('fingerprint')}")
    for r in st.get("reports") or []:
        if r.get("kind") == "deadlock":
            waiting = ", ".join(
                f"{w.get('thread')} on {w.get('on')}"
                for w in r.get("waiting") or [])
            print(f"\nDEADLOCK @ seed {r.get('schedule_seed')} step "
                  f"{r.get('step')} ({r.get('policy')}): [{waiting}]")
            print(f"  replay: operator schedcheck --replay "
                  f"{r.get('schedule_seed')}")
        else:
            print(f"\nDIVERGENCE @ seed {r.get('schedule_seed')}: "
                  f"expected {r.get('expected')} got {r.get('got')} "
                  f"(the scenario changed between record and replay)")
    return 1 if (st.get("deadlock_count")
                 or st.get("divergence_count")) else 0


def cmd_operator_shardcheck(args) -> int:
    """Sharding-discipline sanitizer report (rides /v1/agent/self
    stats.shardcheck): spec drift vs the parallel/mesh.py registry,
    implicit transfers into mesh callables, collective-budget excess
    and per-shard byte parity, each with witness stacks.  Enable with
    NOMAD_TPU_SHARDCHECK=1 on the agent; off is a true no-op and
    reports enabled=False.  ``--compile-audit`` runs LOCALLY (no agent
    round-trip): it compiles the registered mesh programs for an
    8-device CPU mesh and prints the collective/bytes inventory.
    Exit 1 when spec drift, implicit transfers or collective excess
    exist (or the compile audit errors)."""
    from nomad_tpu import shardcheck

    if args.compile_audit:
        shardcheck.ensure_virtual_devices(args.devices)
        inv = shardcheck.compile_audit(n_devices=args.devices,
                                       nodes=args.nodes)
        if "error" in inv:
            print(f"compile-audit error: {inv['error']}")
            return 1
        print(f"mesh         = {inv['mesh']} over {inv['devices']} "
              f"devices")
        print(f"probe shape  = E x P x N = {inv['shape']}")
        print(f"\n{'group':12s} {'total_bytes':>12s} "
              f"{'per_shard_bytes':>16s}")
        for g, row in sorted(inv["per_shard_budget"].items()):
            print(f"{g:12s} {row['total_bytes']:12d} "
                  f"{row['declared_per_shard_bytes']:16d}")
        rc = 0
        for p in inv["programs"]:
            print(f"\nprogram: {p['program']}")
            if "audit_error" in p:
                print(f"  AUDIT ERROR: {p['audit_error']}")
                rc = 1
                continue
            cols = p.get("collectives") or {}
            if cols:
                for op, n in sorted(cols.items()):
                    print(f"  {op:20s} x{n}")
            else:
                print("  (no collectives)")
            for k in ("flops", "bytes_accessed"):
                if k in p:
                    print(f"  {k:20s} {p[k]:.0f}")
        return rc
    api = _client(args)
    st = api.get("/v1/agent/self")["stats"].get("shardcheck") or {}
    for k in ("enabled", "hlo_audit", "wrapped_dispatches",
              "sanctioned_puts", "leaves_checked", "programs_audited",
              "baselines_recorded", "spec_drift_count",
              "implicit_xfer_count", "collective_excess_count",
              "shard_parity_count", "audit_errors",
              "reports_dropped"):
        print(f"{k:24s} = {st.get(k)}")
    if not st.get("enabled") and not st.get("spec_drift_count"):
        print("(checker disabled: set NOMAD_TPU_SHARDCHECK=1 on the "
              "agent to record sharding discipline)")
    for i, r in enumerate(st.get("spec_drift") or []):
        print(f"\nSPEC DRIFT {i}: {r.get('kind')} {r.get('group')}."
              f"{r.get('field')} declared {r.get('declared')} actual "
              f"{r.get('actual')} (amplification "
              f"{r.get('amplification_bytes')} bytes, thread "
              f"{r.get('thread')})")
        if args.stacks:
            for ln in (r.get("stack") or "").rstrip().splitlines():
                print(f"    {ln}")
    for i, r in enumerate(st.get("implicit_xfers") or []):
        print(f"\nIMPLICIT TRANSFER {i}: {r.get('kind')} "
              f"{r.get('group')}.{r.get('field')} ({r.get('bytes')} "
              f"bytes) -- {r.get('detail')}")
        if args.stacks:
            for ln in (r.get("stack") or "").rstrip().splitlines():
                print(f"    {ln}")
    for i, r in enumerate(st.get("collective_excess") or []):
        print(f"\nCOLLECTIVE EXCESS {i}: {r.get('excess')} in "
              f"{r.get('program') or r.get('family')}")
        for ln in r.get("witness_instructions") or []:
            print(f"    {ln}")
    for r in st.get("shard_parity_reports") or []:
        print(f"shard byte parity: {r.get('group')}.{r.get('field')} "
              f"declared {r.get('declared_per_device')} vs actual "
              f"{r.get('actual_per_device')} bytes/device over "
              f"{r.get('devices')} devices")
    return 1 if (st.get("spec_drift_count")
                 or st.get("implicit_xfer_count")
                 or st.get("collective_excess_count")) else 0


def cmd_operator_sanitizers(args) -> int:
    """One-table summary of all five sanitizers (lockcheck, jitcheck,
    statecheck, schedcheck, shardcheck) off /v1/agent/self. Exit 1
    when any hard violation class is non-zero (cycles / steady-state
    retraces / torn reads / aliasing writes / manifested deadlocks /
    spec drift / implicit transfers / collective excess)."""
    api = _client(args)
    stats = api.get("/v1/agent/self")["stats"]
    lc = stats.get("lockcheck") or {}
    jc = stats.get("jitcheck") or {}
    sc = stats.get("statecheck") or {}
    dc = stats.get("schedcheck") or {}
    hc = stats.get("shardcheck") or {}
    rows = [
        ("lockcheck", lc.get("enabled"),
         {"cycles": lc.get("cycle_count", 0),
          "held_across": len(lc.get("held_across") or []),
          "escaped": len(lc.get("escaped") or [])},
         ("cycles",)),
        ("jitcheck", jc.get("enabled"),
         {"retraces": jc.get("retrace_count", 0),
          "host_syncs": jc.get("host_sync_count", 0),
          "x64_leaks": jc.get("x64_leak_count", 0),
          "mutations": jc.get("mutation_count", 0)},
         ("retraces",)),
        ("statecheck", sc.get("enabled"),
         {"torn_reads": sc.get("torn_read_count", 0),
          "aliasing": sc.get("aliasing_write_count", 0),
          "journal_gaps": sc.get("journal_gap_count", 0),
          "write_skews": sc.get("write_skew_count", 0),
          "stale_memos": sc.get("stale_memo_count", 0)},
         ("torn_reads", "aliasing")),
        ("schedcheck", dc.get("enabled"),
         {"deadlocks": dc.get("deadlock_count", 0),
          "divergences": dc.get("divergence_count", 0),
          "preemptions": dc.get("preemptions", 0)},
         ("deadlocks", "divergences")),
        ("shardcheck", hc.get("enabled"),
         {"spec_drift": hc.get("spec_drift_count", 0),
          "implicit_xfer": hc.get("implicit_xfer_count", 0),
          "collective_excess": hc.get("collective_excess_count", 0),
          "shard_parity": hc.get("shard_parity_count", 0)},
         ("spec_drift", "implicit_xfer", "collective_excess")),
    ]
    rc = 0
    print(f"{'sanitizer':12s} {'enabled':8s} {'verdict':8s} findings")
    for name, enabled, counts, hard in rows:
        bad = any(counts.get(k) for k in hard)
        soft = any(v for v in counts.values())
        verdict = ("FAIL" if bad else
                   "warn" if soft else
                   "clean" if enabled else "off")
        if bad:
            rc = 1
        detail = " ".join(f"{k}={v}" for k, v in counts.items())
        print(f"{name:12s} {str(bool(enabled)):8s} {verdict:8s} "
              f"{detail}")
    if rc == 0 and not any(r[1] for r in rows):
        print("(all sanitizers disabled: set NOMAD_TPU_LOCKCHECK/"
              "JITCHECK/STATECHECK/SCHEDCHECK/SHARDCHECK=1 to record)")
    return rc


def cmd_operator_transfers(args) -> int:
    """Transfer & device-residency observatory (rides /v1/agent/self
    stats.xferobs): the per-dispatch payload ledger decomposed by tree
    group (shipped vs cache-resident bytes), the sanctioned-fetch
    result-byte table, the const-cache residency map (per-entry
    bytes/version/age/hits + high watermark), and the live tunnel-model
    fit (rtt/bandwidth/crossover). Exit 1 when the ledger's byte parity
    against nomad.solver.dispatch_bytes_total is nonzero."""
    api = _client(args)
    st = api.get("/v1/agent/self")["stats"].get("xferobs") or {}
    if not st.get("enabled", False):
        print("transfer observatory disabled (NOMAD_TPU_XFEROBS=0)")
        return 0

    def mb(n):
        return f"{(n or 0) / 1048576.0:.3f}"

    for k in ("dispatches", "shipped_bytes_total",
              "resident_bytes_total", "fetched_bytes_total",
              "counter_mirror_bytes", "parity_bytes"):
        print(f"{k:22s} = {st.get(k)}")
    groups = st.get("groups") or {}
    if groups:
        print()
        print(_fmt_table(
            [[g, mb(d["shipped_bytes"]), mb(d["resident_bytes"]),
              str(d["shipped_arrays"]), str(d["resident_arrays"])]
             for g, d in sorted(groups.items())],
            ["Group", "Shipped(MB)", "Resident(MB)", "Ships", "Hits"]))
    fetches = st.get("fetches") or {}
    if fetches:
        print()
        print(_fmt_table(
            [[g, mb(d["bytes"]), str(d["fetches"])]
             for g, d in sorted(fetches.items())],
            ["Fetch", "Bytes(MB)", "Count"]))
    fit = st.get("tunnel")
    print()
    if fit:
        bw = fit.get("bw_mbps")
        xo = fit.get("crossover_bytes")
        # a local (in-process CPU fallback) backend has no tunnel to
        # fit: bandwidth is structurally absent, not merely unsampled
        bw_txt = (f"{bw}MB/s" if bw is not None
                  else "n/a (local backend)")
        print(f"tunnel fit: rtt={fit.get('rtt_ms')}ms "
              f"bw={bw_txt} "
              f"samples={fit.get('samples')} "
              f"residual={fit.get('residual_rms_ms')}ms"
              + (f" crossover={xo}B" if xo is not None else "")
              + (f" (skipped {fit.get('skipped_slow')} compile-slow)"
                 if fit.get("skipped_slow") else ""))
    else:
        print("tunnel fit: insufficient samples")
    res = st.get("residency") or {}
    if res:
        print(f"residency: {res.get('entries')} pinned entries, "
              f"{mb(res.get('resident_bytes'))}MB resident "
              f"(hwm {mb(res.get('resident_hwm_bytes'))}MB, "
              f"{res.get('evictions')} evictions, "
              f"{res.get('invalidations')} invalidations)")
        if res.get("chain_entries"):
            print(f"delta chain: {res.get('chain_entries')} entries, "
                  f"{mb(res.get('chain_resident_bytes'))}MB resident, "
                  f"{res.get('delta_promotions')} promotions / "
                  f"{res.get('delta_reuses')} reuses / "
                  f"{res.get('delta_fallbacks')} fallbacks, "
                  f"{mb(res.get('delta_bytes_total'))}MB delta payload")
        top = res.get("top") or []
        if top:
            # chain rows promote in place: show the base version the
            # device buffer was installed at and how many journal
            # deltas have been applied since
            def chain_col(e):
                if "base_version" in e:
                    return (f"v{e['base_version']}"
                            f"+{e.get('deltas_applied', 0)}d")
                return ""
            print(_fmt_table(
                [[e["id"], mb(e["bytes"]), str(e.get("version")),
                  chain_col(e), f"{e['age_s']:.0f}", str(e["hits"])]
                 for e in top],
                ["Entry", "MB", "Version", "Chain", "Age(s)", "Hits"]))
    return 1 if st.get("parity_bytes") else 0


def _render_trace_waterfall(tr: dict, width: int = 48) -> str:
    """ASCII span waterfall for one eval trace: each span a bar
    positioned/scaled on the trace's wall-clock extent."""
    lines = []
    flag = (f"  DEGRADED({tr.get('degraded_reason')})"
            if tr.get("degraded") else "")
    lines.append(f"Eval      {tr.get('eval_id')}")
    lines.append(f"Status    {tr.get('status')}"
                 f"  dur={tr.get('dur_ms', 0.0):.2f}ms{flag}")
    tags = tr.get("tags") or {}
    if tags:
        lines.append("Tags      " + " ".join(
            f"{k}={v}" for k, v in sorted(tags.items())))
    if tr.get("error"):
        lines.append(f"Error     {tr['error']}")
    spans = tr.get("spans") or []
    if not spans:
        lines.append("(no spans recorded)")
        return "\n".join(lines)
    t0 = min(s["t0"] for s in spans)
    t1 = max(s["t0"] + s["dur_ms"] / 1e3 for s in spans)
    total = max(t1 - t0, 1e-9)
    lines.append("")
    name_w = min(28, max(len(s["name"]) for s in spans) + 1)
    for s in sorted(spans, key=lambda s: (s["t0"], -s["dur_ms"])):
        off = int((s["t0"] - t0) / total * width)
        off = min(off, width - 1)
        ln = max(1, round(s["dur_ms"] / 1e3 / total * width))
        bar = (" " * off + "▇" * min(ln, width - off)).ljust(width)
        stags = " ".join(f"{k}={v}"
                         for k, v in sorted(
                             (s.get("tags") or {}).items()))
        lines.append(f"  {s['name']:<{name_w}} |{bar}| "
                     f"{s['dur_ms']:>9.2f}ms  {stags}".rstrip())
    if tr.get("truncated_spans"):
        lines.append(f"  ... {tr['truncated_spans']} spans truncated "
                     "(NOMAD_TPU_TRACE_MAX_SPANS)")
    return "\n".join(lines)


def cmd_operator_trace(args) -> int:
    """Eval trace forensics (rides GET /v1/agent/trace): fetch one
    eval's span waterfall, or list/render the slowest or degraded
    retained traces."""
    api = _client(args)
    if args.eval_id:
        try:
            tr = api.get(f"/v1/agent/trace/{args.eval_id}")
        except ApiError as e:
            print(f"No trace for eval {args.eval_id!r}: {e}",
                  file=sys.stderr)
            return 1
        print(_render_trace_waterfall(tr))
        if getattr(args, "quality", False):
            print()
            _print_quality_summary(api)
        return 0
    params = {}
    if args.degraded:
        params["degraded"] = "1"
    if args.slowest:
        params["slowest"] = str(args.slowest)
    reply = api.get("/v1/agent/trace", **params)
    traces = reply.get("traces", [])
    stats = reply.get("stats", {})
    if not traces:
        print("No retained traces"
              + ("" if stats.get("enabled", True)
                 else " (tracing disabled: NOMAD_TPU_TRACE=0)")
              + f"; {stats.get('dropped', 0)} dropped/sampled out.")
        if getattr(args, "quality", False):
            print()
            _print_quality_summary(api)
        return 0
    print(_fmt_table(
        [[t["eval_id"][:16], t.get("tags", {}).get("lane", "-"),
          f"{t['dur_ms']:.1f}", str(t["spans"]),
          (t.get("degraded_reason") or
           ("error" if t.get("error") else "-")), t["status"]]
         for t in traces],
        ["Eval", "Lane", "Duration(ms)", "Spans", "Degraded",
         "Status"]))
    if args.slowest:
        # --slowest N renders each returned trace's waterfall in full
        for t in traces:
            try:
                full = api.get(f"/v1/agent/trace/{t['eval_id']}")
            except ApiError:
                continue
            print()
            print(_render_trace_waterfall(full))
    if getattr(args, "quality", False):
        # degraded-eval triage context: were the degraded evals also
        # DRIFTING (shadow audit), and which stage is saturated?
        print()
        _print_quality_summary(api)
    return 0


def _print_quality_summary(api) -> None:
    try:
        rep = api.get("/v1/operator/quality")
    except ApiError as e:
        print(f"(quality report unavailable: {e})")
        return
    if not rep.get("enabled"):
        print("quality observatory disabled (NOMAD_TPU_QUALITY=0)")
        return
    a = rep.get("audit") or {}
    print(f"shadow audit   audited={a.get('audited', 0)} "
          f"drift_max={a.get('score_drift_max', 0.0)} "
          f"mismatches={a.get('decision_mismatch_total', 0)}"
          + (f"  ALERT({a['alert']['reason']})" if a.get("alert")
             else ""))
    sat = rep.get("saturation") or {}
    if sat.get("bottleneck"):
        b = sat["stages"][sat["bottleneck"]]
        print(f"bottleneck     {sat['bottleneck']} "
              f"(L={b['littles_l']}, busy={b['busy_pct']}%, "
              f"p99={b['p99_ms']}ms)")


def cmd_operator_quality(args) -> int:
    """Quality scoreboard + shadow-oracle audit + pipeline saturation
    attribution (rides GET /v1/operator/quality)."""
    api = _client(args)
    rep = api.get("/v1/operator/quality")
    if not rep.get("enabled"):
        print("quality observatory disabled (NOMAD_TPU_QUALITY=0)")
        return 0
    p = rep.get("placement") or {}
    if not p.get("attached"):
        print("quality observatory not attached to a running server")
    else:
        fleet = p["fleet"]
        print(f"fleet          {fleet['nodes']} nodes "
              f"({fleet['ready']} ready, {fleet['occupied']} occupied), "
              f"{fleet['live_allocs']} live allocs")
        print(f"fragmentation  {p['fragmentation_index']}")
        pe = p["packing_efficiency"]
        print(f"packing_eff    cpu={pe['cpu']} mem={pe['mem']}")
        for dim in ("cpu", "mem"):
            u = p["utilization"][dim]
            bars = "".join(
                " .:-=+*#%@"[min(9, int(c * 9 / max(max(u["hist"]), 1)))]
                for c in u["hist"])
            print(f"util[{dim}]      mean={u['mean']} p50={u['p50']} "
                  f"p90={u['p90']} max={u['max']}  |{bars}| (0->1)")
        churn = p["churn"]
        print("churn          " + " ".join(
            f"{k}={churn[k]}" for k in
            ("placements", "stops", "preemptions", "reschedules",
             "completions", "failures", "rejected_nodes")))
        for name, s in sorted((p.get("scores") or {}).items()):
            print(f"score[{name}]  n={s['count']} "
                  f"mean={s['mean']:.4f} p50={s.get('p50', 0):.4f} "
                  f"p99={s.get('p99', 0):.4f}")
    _print_quality_summary(api)
    sat = rep.get("saturation") or {}
    stages = sat.get("stages") or {}
    if stages:
        print()
        print(_fmt_table(
            [[st, d["kind"], str(d["count"]), f"{d['mean_ms']:.2f}",
              f"{d['p99_ms']:.2f}", f"{d['busy_pct']:.2f}",
              f"{d['littles_l']:.3f}",
              f"{d['share_of_recorded_pct']:.1f}"]
             for st, d in sorted(stages.items())],
            ["Stage", "Kind", "Count", "Mean(ms)", "p99(ms)",
             "Busy%", "L", "Share%"]))
    return 0


def cmd_operator_snapshot(args) -> int:
    api = _client(args)
    if args.sub2 == "save":
        data = api.snapshot_save()
        with open(args.file, "wb") as f:
            f.write(data)
        print(f"Snapshot written to {args.file} ({len(data)} bytes)")
    elif args.sub2 == "restore":
        with open(args.file, "rb") as f:
            reply = api.snapshot_restore(f.read())
        print(f"Snapshot restored (index {reply.get('index')})")
    return 0


def cmd_service(args) -> int:
    api = _client(args)
    if args.sub2 == "list":
        print(_fmt_table(
            [[s["service_name"], ",".join(s["tags"]) or "-"]
             for s in api.services()],
            ["Service", "Tags"]))
    elif args.sub2 == "info":
        regs = api.service(args.name)
        print(_fmt_table(
            [[r["id"][:24], f'{r["address"]}:{r["port"]}',
              r["alloc_id"][:8], r["node_id"][:8]] for r in regs],
            ["ID", "Address", "Alloc", "Node"]))
    return 0


def cmd_volume(args) -> int:
    api = _client(args)
    if args.sub2 == "status":
        if getattr(args, "id", ""):
            v = api.csi_volume(args.id)
            print(json.dumps(v, indent=2, default=str))
        else:
            print(_fmt_table(
                [[v["id"], v["plugin_id"], v["access_mode"],
                  str(v["schedulable"]),
                  f'{v["read_claims"]}r/{v["write_claims"]}w']
                 for v in api.csi_volumes()],
                ["ID", "Plugin", "AccessMode", "Schedulable", "Claims"]))
    elif args.sub2 == "register":
        with open(args.file) as f:
            body = json.load(f)
        api.register_csi_volume(body["id"], body.get("plugin_id", ""),
                                **{k: v for k, v in body.items()
                                   if k not in ("id", "plugin_id")})
        print(f"Volume {body['id']!r} registered")
    elif args.sub2 == "create":
        # (reference: command/volume_create.go -- dynamic provisioning)
        body = {}
        if args.file:
            with open(args.file) as f:
                loaded = json.load(f)
            if not isinstance(loaded, dict):
                print("Error: -file must contain a JSON object",
                      file=sys.stderr)
                return 1
            body.update(loaded)
        # the explicit flag always wins over a reused spec file
        body["plugin_id"] = args.plugin
        out = api.post(f"/v1/volume/csi/{args.id}/create", body)
        print(f"Volume {args.id!r} created via "
              f"{body.get('plugin_id', '')!r}: {out.get('volume', {})}")
    elif args.sub2 == "delete":
        api.post(f"/v1/volume/csi/{args.id}/delete", {})
        print(f"Volume {args.id!r} deleted")
    elif args.sub2 == "deregister":
        api.deregister_csi_volume(args.id, force=args.force)
        print(f"Volume {args.id!r} deregistered")
    return 0


def cmd_plugin(args) -> int:
    api = _client(args)
    if getattr(args, "id", ""):
        print(json.dumps(api.csi_plugin(args.id), indent=2, default=str))
    else:
        print(_fmt_table(
            [[p["id"], str(p["nodes_healthy"])] for p in api.csi_plugins()],
            ["ID", "NodesHealthy"]))
    return 0


def cmd_status(args) -> int:
    """Cross-object prefix search, like `nomad status <prefix>`."""
    reply = _client(args).search(args.prefix)
    rows = []
    for ctx, ids in sorted(reply.get("matches", {}).items()):
        for i in ids:
            rows.append([ctx, i])
    if not rows:
        print(f"No matches for {args.prefix!r}")
        return 1
    print(_fmt_table(rows, ["Type", "ID"]))
    return 0


def cmd_version(args) -> int:
    from .client.fingerprint import VERSION
    print(f"nomad-tpu v{VERSION} (tpu-native cluster scheduler)")
    return 0


# ---------------------------------------------------------------------------
def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="nomad-tpu")
    p.add_argument("-address", dest="address", default="")
    p.add_argument("-namespace", dest="namespace", default="default")
    sub = p.add_subparsers(dest="cmd", required=True)

    ag = sub.add_parser("agent", help="run the dev agent")
    ag.add_argument("-dev", action="store_true", default=True)
    ag.add_argument("--nodes", type=int, default=3)
    ag.add_argument("--port", type=int, default=4646)
    ag.add_argument("--workers", type=int, default=2)
    ag.add_argument("--tpu", action="store_true")
    ag.set_defaults(fn=cmd_agent)

    job = sub.add_parser("job", help="job commands").add_subparsers(
        dest="sub", required=True)
    jr = job.add_parser("run")
    jr.add_argument("file")
    jr.add_argument("-var", action="append", default=[])
    jr.set_defaults(fn=cmd_job_run)
    jp = job.add_parser("plan")
    jp.add_argument("file")
    jp.add_argument("-var", action="append", default=[])
    jp.set_defaults(fn=cmd_job_plan)
    js = job.add_parser("status")
    js.add_argument("id", nargs="?", default="")
    js.set_defaults(fn=cmd_job_status)
    jst = job.add_parser("stop")
    jst.add_argument("id")
    jst.add_argument("-purge", action="store_true")
    jst.set_defaults(fn=cmd_job_stop)
    ji = job.add_parser("inspect")
    ji.add_argument("id")
    ji.set_defaults(fn=cmd_job_inspect)
    jh = job.add_parser("history")
    jh.add_argument("id")
    jh.set_defaults(fn=cmd_job_history)
    jrev = job.add_parser("revert")
    jrev.add_argument("id")
    jrev.add_argument("version", type=int)
    jrev.set_defaults(fn=cmd_job_revert)
    jd = job.add_parser("dispatch")
    jd.add_argument("id")
    jd.add_argument("payload_file", nargs="?", default="")
    jd.add_argument("-meta", action="append", default=[])
    jd.add_argument("-idempotency-token", dest="idempotency_token",
                    default="")
    jd.set_defaults(fn=cmd_job_dispatch)
    jsc = job.add_parser("scale")
    jsc.add_argument("id")
    jsc.add_argument("group")
    jsc.add_argument("count", type=int)
    jsc.add_argument("-message", default="")
    jsc.set_defaults(fn=cmd_job_scale)

    node = sub.add_parser("node", help="node commands").add_subparsers(
        dest="sub", required=True)
    ns = node.add_parser("status")
    ns.add_argument("id", nargs="?", default="")
    ns.set_defaults(fn=cmd_node_status)
    nst = node.add_parser("stats")
    nst.add_argument("id", nargs="?", default="")
    nst.set_defaults(fn=cmd_node_stats)
    npg = node.add_parser("purge")
    npg.add_argument("id")
    npg.set_defaults(fn=cmd_node_purge)
    nd = node.add_parser("drain")
    nd.add_argument("id")
    g = nd.add_mutually_exclusive_group(required=True)
    g.add_argument("-enable", dest="enable", action="store_true")
    g.add_argument("-disable", dest="enable", action="store_false")
    nd.add_argument("-deadline", type=float, default=3600.0)
    nd.set_defaults(fn=cmd_node_drain)
    ne = node.add_parser("eligibility")
    ne.add_argument("id")
    g = ne.add_mutually_exclusive_group(required=True)
    g.add_argument("-enable", dest="enable", action="store_true")
    g.add_argument("-disable", dest="enable", action="store_false")
    ne.set_defaults(fn=cmd_node_eligibility)

    al = sub.add_parser("alloc", help="alloc commands").add_subparsers(
        dest="sub", required=True)
    als = al.add_parser("status")
    als.add_argument("id")
    als.set_defaults(fn=cmd_alloc_status)
    alst = al.add_parser("stop")
    alst.add_argument("id")
    alst.set_defaults(fn=cmd_alloc_stop)
    alsg = al.add_parser("signal")
    alsg.add_argument("-task", required=True)
    alsg.add_argument("-s", dest="signal", default="SIGUSR1")
    alsg.add_argument("id")
    alsg.set_defaults(fn=cmd_alloc_signal)
    alrs = al.add_parser("restart")
    alrs.add_argument("-task", default="")
    alrs.add_argument("id")
    alrs.set_defaults(fn=cmd_alloc_restart)
    alex = al.add_parser("exec")
    alex.add_argument("-task", required=True)
    alex.add_argument("-timeout", type=float, default=10.0)
    alex.add_argument("id")
    alex.add_argument("cmd", nargs="+")
    alex.set_defaults(fn=cmd_alloc_exec)
    alfs = al.add_parser("fs")
    alfs.add_argument("id")
    alfs.add_argument("path", nargs="?", default="/")
    alfs.set_defaults(fn=cmd_alloc_fs)
    allog = al.add_parser("logs")
    allog.add_argument("id")
    allog.add_argument("task")
    allog.add_argument("-stderr", action="store_true")
    allog.add_argument("-tail", type=int, default=0, metavar="BYTES",
                       help="show only the last BYTES bytes of output "
                            "(byte count, like the reference's -c; "
                            "use -n for line semantics)")
    allog.add_argument("-n", dest="lines", type=int, default=0,
                       metavar="LINES",
                       help="show only the last LINES lines of output "
                            "(the reference CLI's `-tail -n` "
                            "semantics)")
    allog.add_argument("-f", action="store_true",
                       help="follow: stream new output until the alloc "
                            "stops (combine with -tail/-n)")
    allog.set_defaults(fn=cmd_alloc_logs)

    ev = sub.add_parser("eval", help="eval commands")
    ev.add_argument("id", nargs="?", default="")
    ev.set_defaults(fn=cmd_eval)

    dep = sub.add_parser("deployment", help="deployment commands")
    depsub = dep.add_subparsers(dest="sub")
    dep.set_defaults(fn=cmd_deployment)
    for op_name in ("promote", "pause", "resume", "fail"):
        dop = depsub.add_parser(op_name)
        if op_name == "promote":
            # (reference: command/deployment_promote.go -group)
            dop.add_argument("-group", action="append", default=[])
        dop.add_argument("id")
        dop.set_defaults(fn=cmd_deployment_op)
    depls = depsub.add_parser("list")
    depls.set_defaults(fn=cmd_deployment)

    op = sub.add_parser("operator").add_subparsers(dest="sub",
                                                   required=True)
    osch = op.add_parser("scheduler")
    osch.add_argument("-scheduler-algorithm", dest="algorithm", default="")
    osch.add_argument("-memory-oversubscription", dest="memory_oversub",
                      action="store_true")
    osch.set_defaults(fn=cmd_operator_scheduler)
    osn = op.add_parser("snapshot").add_subparsers(dest="sub2",
                                                   required=True)
    osns = osn.add_parser("save")
    osns.add_argument("file")
    osns.set_defaults(fn=cmd_operator_snapshot)
    osnr = osn.add_parser("restore")
    osnr.add_argument("file")
    osnr.set_defaults(fn=cmd_operator_snapshot)
    okr = op.add_parser("keyring").add_subparsers(dest="sub2",
                                                  required=True)
    okr.add_parser("list").set_defaults(fn=cmd_operator_keyring)
    okr.add_parser("rotate").set_defaults(fn=cmd_operator_keyring)
    orf = op.add_parser("raft").add_subparsers(dest="sub2", required=True)
    orf.add_parser("list-peers").set_defaults(fn=cmd_operator_raft)
    orp = orf.add_parser("remove-peer")
    orp.add_argument("id")
    orp.set_defaults(fn=cmd_operator_raft)
    odbg = op.add_parser("debug")
    odbg.add_argument("-duration", type=float, default=2.0)
    odbg.add_argument("-output", default="")
    odbg.set_defaults(fn=cmd_operator_debug)
    osol = op.add_parser("solver").add_subparsers(dest="sub2",
                                                  required=True)
    osol.add_parser("status").set_defaults(fn=cmd_operator_solver)
    osol.add_parser("reprobe").set_defaults(fn=cmd_operator_solver)
    onode = op.add_parser("node").add_subparsers(dest="sub2",
                                                 required=True)
    onode.add_parser("flaps",
                     help="per-node flap scores + active quarantines"
                     ).set_defaults(fn=cmd_operator_node_flaps)
    op.add_parser("workers",
                  help="supervised scheduler worker pool state "
                  "(liveness, progress heartbeats, restarts)"
                  ).set_defaults(fn=cmd_operator_workers)
    oevals = op.add_parser("evals").add_subparsers(dest="sub2",
                                                   required=True)
    oq = oevals.add_parser("quarantine",
                           help="poison-eval dead letters; release "
                           "with --release <id> / --release-all")
    oq.add_argument("--release", metavar="EVAL_ID", default=None,
                    help="re-admit one quarantined eval")
    oq.add_argument("--release-all", action="store_true",
                    dest="release_all",
                    help="re-admit every quarantined eval")
    oq.set_defaults(fn=cmd_operator_evals_quarantine)
    olc = op.add_parser("lockcheck",
                        help="lock-order sanitizer report (cycles, "
                        "held-across, escaped-frame acquires)")
    olc.add_argument("--stacks", action="store_true",
                     help="print the witness stacks under each finding")
    olc.set_defaults(fn=cmd_operator_lockcheck)
    osc = op.add_parser("statecheck",
                        help="MVCC snapshot-isolation sanitizer report "
                        "(torn reads / aliasing writes / journal gaps "
                        "/ write skew / stale memos)")
    osc.add_argument("--stacks", action="store_true",
                     help="print witness stacks per finding")
    osc.set_defaults(fn=cmd_operator_statecheck)
    osan = op.add_parser("sanitizers",
                         help="one-table summary of lockcheck + "
                         "jitcheck + statecheck + schedcheck + "
                         "shardcheck state")
    osan.set_defaults(fn=cmd_operator_sanitizers)
    ohc = op.add_parser("shardcheck",
                        help="sharding-discipline sanitizer report "
                        "(spec drift / implicit transfers / "
                        "collective budget / per-shard byte parity), "
                        "or an offline mesh-program compile audit")
    ohc.add_argument("--stacks", action="store_true",
                     help="print witness stacks per finding")
    ohc.add_argument("--compile-audit", action="store_true",
                     dest="compile_audit",
                     help="compile the registered mesh programs for "
                     "a virtual CPU mesh and print the collective/"
                     "bytes inventory (local; no agent round-trip)")
    ohc.add_argument("--devices", type=int, default=8,
                     help="device count for --compile-audit "
                     "(default 8)")
    ohc.add_argument("--nodes", type=int, default=256,
                     help="probe fleet size for --compile-audit "
                     "(default 256; rounded to the mesh node axis)")
    ohc.set_defaults(fn=cmd_operator_shardcheck)
    odc = op.add_parser("schedcheck",
                        help="deterministic schedule explorer report, "
                        "seeded replay of a recorded interleaving, or "
                        "a local seed sweep")
    odc.add_argument("--replay", type=int, default=None, metavar="SEED",
                     help="re-run the scenario under this exact "
                     "schedule seed (local; lockcheck+statecheck "
                     "armed)")
    odc.add_argument("--explore", type=int, default=None, metavar="N",
                     help="sweep N schedule seeds locally and "
                     "aggregate violations")
    odc.add_argument("--scenario", default="broker-smoke",
                     help="built-in scenario for --replay/--explore "
                     "(broker-smoke, planted-write-skew, "
                     "planted-torn-read)")
    odc.add_argument("--policy", default=None,
                     help="schedule policy: random (default), pct, rr")
    odc.set_defaults(fn=cmd_operator_schedcheck)
    ojc = op.add_parser("jitcheck",
                        help="dispatch-discipline sanitizer report "
                        "(steady-state retraces, hot-path host syncs, "
                        "dtype drift, cache mutations)")
    ojc.add_argument("--sites", action="store_true",
                     help="print the per-call-site trace table")
    ojc.set_defaults(fn=cmd_operator_jitcheck)
    otx = op.add_parser("transfers",
                        help="transfer ledger + device-residency map "
                        "+ live tunnel-model fit (xferobs)")
    otx.set_defaults(fn=cmd_operator_transfers)
    otr = op.add_parser("trace",
                        help="eval span-waterfall forensics")
    otr.add_argument("eval_id", nargs="?", default="")
    otr.add_argument("--slowest", type=int, default=0,
                     help="render the N slowest retained traces")
    otr.add_argument("--degraded", action="store_true",
                     help="only degraded/errored traces")
    otr.add_argument("--quality", action="store_true",
                     help="append the quality scoreboard / shadow-audit"
                     " context (drift, mismatches, bottleneck) below"
                     " the traces")
    otr.set_defaults(fn=cmd_operator_trace)
    oq = op.add_parser("quality",
                       help="placement-quality scoreboard, shadow-"
                       "oracle audit + pipeline saturation report")
    oq.set_defaults(fn=cmd_operator_quality)

    mon = sub.add_parser("monitor")
    mon.add_argument("-log-level", dest="log_level", default="info")
    mon.set_defaults(fn=cmd_monitor)

    srv = sub.add_parser("server").add_subparsers(dest="sub",
                                                  required=True)
    sm = srv.add_parser("members")
    sm.set_defaults(fn=cmd_server_members)

    sysp = sub.add_parser("system").add_subparsers(dest="sub",
                                                   required=True)
    sg = sysp.add_parser("gc")
    sg.set_defaults(fn=cmd_system_gc)

    var = sub.add_parser("var", help="secure variables").add_subparsers(
        dest="sub", required=True)
    vp = var.add_parser("put")
    vp.add_argument("path")
    vp.add_argument("items", nargs="+", help="key=value ...")
    vp.add_argument("-check-index", dest="cas", type=int, default=None)
    vp.set_defaults(fn=cmd_var_put)
    vg = var.add_parser("get")
    vg.add_argument("path")
    vg.set_defaults(fn=cmd_var_get)
    vl = var.add_parser("list")
    vl.add_argument("prefix", nargs="?", default="")
    vl.set_defaults(fn=cmd_var_list)
    vpu = var.add_parser("purge")
    vpu.add_argument("path")
    vpu.add_argument("-check-index", dest="cas", type=int, default=None)
    vpu.set_defaults(fn=cmd_var_purge)

    aclp = sub.add_parser("acl", help="ACL management").add_subparsers(
        dest="sub", required=True)
    ab = aclp.add_parser("bootstrap")
    ab.set_defaults(fn=cmd_acl_bootstrap)
    apol = aclp.add_parser("policy").add_subparsers(dest="sub2",
                                                    required=True)
    apa = apol.add_parser("apply")
    apa.add_argument("name")
    apa.add_argument("file")
    apa.add_argument("-description", default="")
    apa.set_defaults(fn=cmd_acl_policy_apply)
    atok = aclp.add_parser("token").add_subparsers(dest="sub2",
                                                   required=True)
    atc = atok.add_parser("create")
    atc.add_argument("-name", default="")
    atc.add_argument("-type", default="client",
                     choices=["client", "management"])
    atc.add_argument("-policy", action="append")
    atc.add_argument("-role", action="append")
    atc.set_defaults(fn=cmd_acl_token_create)
    arole = aclp.add_parser("role").add_subparsers(dest="sub2",
                                                   required=True)
    ara = arole.add_parser("apply")
    ara.add_argument("name")
    ara.add_argument("-policy", action="append")
    ara.add_argument("-description", default="")
    ara.set_defaults(fn=cmd_acl_role)
    arole.add_parser("list").set_defaults(fn=cmd_acl_role)
    ard = arole.add_parser("delete")
    ard.add_argument("name")
    ard.set_defaults(fn=cmd_acl_role)

    mt = sub.add_parser("metrics")
    mt.set_defaults(fn=cmd_metrics)

    nsp = sub.add_parser("namespace").add_subparsers(dest="sub2",
                                                     required=True)
    nsl = nsp.add_parser("list")
    nsl.set_defaults(fn=cmd_namespace)
    nsa = nsp.add_parser("apply")
    nsa.add_argument("name")
    nsa.add_argument("-description", default="")
    nsa.set_defaults(fn=cmd_namespace)
    nsd = nsp.add_parser("delete")
    nsd.add_argument("name")
    nsd.set_defaults(fn=cmd_namespace)

    npp = sub.add_parser("node-pool").add_subparsers(dest="sub2",
                                                     required=True)
    npl = npp.add_parser("list")
    npl.set_defaults(fn=cmd_node_pool)
    npa = npp.add_parser("apply")
    npa.add_argument("name")
    npa.add_argument("-description", default="")
    npa.add_argument("-scheduler-algorithm", dest="scheduler_algorithm",
                     default="")
    npa.set_defaults(fn=cmd_node_pool)
    npd = npp.add_parser("delete")
    npd.add_argument("name")
    npd.set_defaults(fn=cmd_node_pool)
    npn = npp.add_parser("nodes")
    npn.add_argument("name")
    npn.set_defaults(fn=cmd_node_pool)

    svc = sub.add_parser("service").add_subparsers(dest="sub2",
                                                   required=True)
    svl = svc.add_parser("list")
    svl.set_defaults(fn=cmd_service)
    svi = svc.add_parser("info")
    svi.add_argument("name")
    svi.set_defaults(fn=cmd_service)

    vol = sub.add_parser("volume").add_subparsers(dest="sub2",
                                                  required=True)
    vs = vol.add_parser("status")
    vs.add_argument("id", nargs="?", default="")
    vs.set_defaults(fn=cmd_volume)
    vreg = vol.add_parser("register")
    vreg.add_argument("file")
    vreg.set_defaults(fn=cmd_volume)
    vdereg = vol.add_parser("deregister")
    vdereg.add_argument("id")
    vdereg.add_argument("-force", action="store_true")
    vdereg.set_defaults(fn=cmd_volume)
    vcr = vol.add_parser("create")
    vcr.add_argument("-plugin", required=True)
    vcr.add_argument("-file", default="")
    vcr.add_argument("id")
    vcr.set_defaults(fn=cmd_volume)
    vdel = vol.add_parser("delete")
    vdel.add_argument("id")
    vdel.set_defaults(fn=cmd_volume)

    plg = sub.add_parser("plugin").add_subparsers(dest="sub2",
                                                  required=True)
    ps = plg.add_parser("status")
    ps.add_argument("id", nargs="?", default="")
    ps.set_defaults(fn=cmd_plugin)

    st = sub.add_parser("status", help="prefix search across objects")
    st.add_argument("prefix")
    st.set_defaults(fn=cmd_status)

    vr = sub.add_parser("version")
    vr.set_defaults(fn=cmd_version)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    except (OSError, json.JSONDecodeError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
