"""Fault-injection framework: named failure points armed via env/HTTP.

Round 5's artifact chain proved the stall class this exists to test: the
TPU tunnel wedged MID-ROUND and the eval pipeline had no way to rehearse
that failure before it happened live (TPU_PROBE_JOURNAL.log 07:03Z).
Every component that can hang, error or lag in production declares a
named injection point; tests/test_chaos.py (and operators, via
/v1/operator/faults) arm faults at those points and assert the system
degrades the way the design promises -- bounded-time host fallback,
breaker trip + auto-recovery, broker nack/requeue, no lost evals.

Points wired through the codebase:

  solver.dispatch   solver/service.py + solver/batch.py -- fires INSIDE
                    the watchdog deadline, so hang faults exercise the
                    timeout path (guard.run_dispatch)
  solver.probe      solver/guard.py -- the breaker's recovery probe;
                    an armed fault keeps the breaker open (how chaos
                    tests hold "the tunnel is still wedged")
  worker.invoke     server/worker.py invoke_scheduler -- an armed error
                    nacks the eval (broker requeue must not lose it)
  worker.crash      server/worker.py Worker.run / BatchWorker._run_batch
                    -- an armed error KILLS the worker thread mid-eval
                    (no nack: the leased eval is orphaned until the
                    broker's nack-timeout sweep redelivers it; the
                    WorkerSupervisor must restart the pool slot)
  plan.apply        server/plan_apply.py Planner.apply
  plan.commit       state/store.py apply_plan_results_batch -- fires
                    per plan BEFORE its writes stage, so an armed fault
                    splits a group commit around the injected plan
                    (survivors commit exactly once)
  broker.dequeue    server/broker.py EvalBroker.dequeue
  heartbeat         server/core.py Server.heartbeat
  raft.rpc          raft/transport.py TcpTransport.send (delay/drop)
  quality.skew      server/quality.py shadow-audit capture -- an armed
                    error corrupts the captured solve's scores the way
                    real solver numerics drift would, so chaos drills
                    prove the drift gauge + audit alert fire
                    (placements themselves are untouched)

Actions: ``error`` raises InjectedFault; ``drop`` raises InjectedDrop
(a ConnectionError, so transport callers treat it as a network failure);
``delay`` sleeps ``delay_s`` then continues; ``hang`` blocks until the
fault is disarmed (bounded by ``delay_s`` when given, else effectively
forever -- the watchdog deadline is what must save the caller).

Arming: programmatic (``faults.arm(...)``), HTTP
(``POST /v1/operator/faults``, operator:write), or the
``NOMAD_TPU_FAULT_INJECT`` env var at process start --
``point=action[:delay_s[:count]]`` entries separated by commas, e.g.
``NOMAD_TPU_FAULT_INJECT="solver.dispatch=hang,raft.rpc=delay:0.05:10"``.

The unarmed fast path is one attribute read -- safe on hot paths
(every RPC send and broker dequeue fires a point).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

from . import lockcheck

ACTIONS = ("error", "drop", "delay", "hang")

# The registered point inventory: every ``faults.fire(point)`` call
# site in the tree must name a member (scripts/nomadlint.py
# fire-registered rule parses this tuple; tests/test_chaos.py pins the
# chaos-suite inventory against it). Register the point HERE in the
# same change that adds the call site, with the module that fires it.
POINTS = (
    "solver.dispatch",      # solver/guard.py (inside the watchdog)
    "solver.probe",         # solver/guard.py (breaker recovery probe)
    "worker.invoke",        # server/worker.py invoke_scheduler
    "worker.crash",         # server/worker.py worker loops (kills thread)
    "plan.apply",           # server/plan_apply.py Planner.apply
    "plan.commit",          # state/store.py apply_plan_results_batch
    "broker.dequeue",       # server/broker.py EvalBroker.dequeue
    "heartbeat",            # server/core.py Server.heartbeat
    "raft.rpc",             # raft/transport.py TcpTransport.send
    "quality.skew",         # server/quality.py shadow-audit capture
)


class InjectedFault(Exception):
    """Raised at an armed injection point (action=error)."""


class InjectedDrop(ConnectionError):
    """Raised at an armed injection point (action=drop): looks like a
    network failure to transport-layer callers."""


class _Fault:
    __slots__ = ("point", "action", "delay_s", "count", "fired", "release")

    def __init__(self, point: str, action: str, delay_s: float,
                 count: Optional[int]):
        self.point = point
        self.action = action
        self.delay_s = delay_s
        self.count = count          # remaining injections; None = unlimited
        self.fired = 0
        self.release = threading.Event()    # set on disarm: wakes hangs

    def snapshot(self) -> dict:
        return {"point": self.point, "action": self.action,
                "delay_s": self.delay_s, "count": self.count,
                "fired": self.fired}


class FaultRegistry:
    """Process-global registry of armed faults, keyed by point name."""

    def __init__(self):
        self._lock = threading.Lock()
        self._faults: Dict[str, _Fault] = {}
        self._armed = False          # lock-free fast-path gate
        self._arm_from_env()

    def _arm_from_env(self) -> None:
        spec = os.environ.get("NOMAD_TPU_FAULT_INJECT", "").strip()
        if not spec:
            return
        for entry in spec.split(","):
            entry = entry.strip()
            if not entry or "=" not in entry:
                continue
            point, _, rhs = entry.partition("=")
            parts = rhs.split(":")
            action = parts[0] or "error"
            delay = float(parts[1]) if len(parts) > 1 and parts[1] else 0.0
            count = (int(parts[2])
                     if len(parts) > 2 and parts[2] else None)
            try:
                self.arm(point.strip(), action, delay_s=delay, count=count)
            except ValueError:
                continue            # a typo'd env entry must not abort boot

    # ------------------------------------------------------------------
    def arm(self, point: str, action: str = "error", delay_s: float = 0.0,
            count: Optional[int] = None) -> dict:
        if action not in ACTIONS:
            raise ValueError(f"unknown fault action {action!r} "
                             f"(one of {ACTIONS})")
        if not point:
            raise ValueError("fault point name required")
        f = _Fault(point, action, float(delay_s),
                   int(count) if count is not None else None)
        with self._lock:
            old = self._faults.get(point)
            if old is not None:
                old.release.set()
            self._faults[point] = f
            self._armed = True
        from .server.logbroker import log as _log
        _log("warn", "faultinject",
             f"armed {point}={action} delay={delay_s} count={count}")
        return f.snapshot()

    def disarm(self, point: str) -> bool:
        with self._lock:
            f = self._faults.pop(point, None)
            self._armed = bool(self._faults)
        if f is None:
            return False
        f.release.set()              # wake any thread hung at this point
        from .server.logbroker import log as _log
        _log("warn", "faultinject", f"disarmed {point}")
        return True

    def disarm_all(self) -> int:
        with self._lock:
            faults = list(self._faults.values())
            self._faults.clear()
            self._armed = False
        for f in faults:
            f.release.set()
        return len(faults)

    def snapshot(self) -> dict:
        with self._lock:
            return {"faults": [f.snapshot()
                               for f in self._faults.values()]}

    # ------------------------------------------------------------------
    def fire(self, point: str) -> None:
        """Called at an injection point. No-op unless the point is armed
        (one attribute read on the unarmed path, plus one module-attr
        read for the lock sanitizer, active only under
        NOMAD_TPU_LOCKCHECK=1)."""
        if lockcheck._ACTIVE:
            # a fault point may hang/raise BY DESIGN: holding a lock
            # across one turns an injected solver wedge into a
            # control-plane wedge (lockcheck held_across report)
            lockcheck.note_fire(point)
        if not self._armed:
            return
        with self._lock:
            f = self._faults.get(point)
            if f is None:
                return
            f.fired += 1
            if f.count is not None:
                f.count -= 1
                if f.count <= 0:
                    del self._faults[point]
                    self._armed = bool(self._faults)
                    f.release.set()
        from .server.telemetry import metrics
        metrics.incr(f"nomad.fault.injected.{point}")
        if f.action == "delay":
            time.sleep(f.delay_s)
            return
        if f.action == "hang":
            # blocks until disarmed (or delay_s when bounded); callers
            # are expected to survive via their own watchdog deadline
            f.release.wait(f.delay_s if f.delay_s > 0 else None)
            return
        if f.action == "drop":
            raise InjectedDrop(f"injected fault: {point} dropped")
        raise InjectedFault(f"injected fault: {point}")

    def _reset_for_tests(self) -> None:
        self.disarm_all()


# Process-global registry; `fire` is the hot-path entry point.
faults = FaultRegistry()
fire = faults.fire
