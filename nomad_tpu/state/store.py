"""MVCC state store with index-watch blocking queries.

Semantic parity with /root/reference/nomad/state/state_store.go over
go-memdb: every write bumps a monotone raft-style index, reads run against
cheap snapshots (copy-on-write dict views -- objects are replaced on write,
never mutated in place, which is what makes snapshots safe to share with
concurrently-running scheduler workers, mirroring the immutable-radix
guarantee), and watchers block until a table index advances
(reference: nomad/rpc.go:852 blockingRPC + go-memdb WatchSet).
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from .. import schedcheck
from .alloc_table import AllocTable
from ..structs import (
    ACL_TOKEN_TYPE_MANAGEMENT, ACLPolicy, ACLToken, Allocation, Deployment,
    Evaluation, Job, Namespace, Node, NodePool, Plan, PlanResult, RootKey,
    ScalingEvent, ScalingPolicy, SchedulerConfiguration, VariableEncrypted,
    ALLOC_DESIRED_STOP, ALLOC_CLIENT_FAILED, ALLOC_CLIENT_LOST,
    ALLOC_CLIENT_COMPLETE,
    EVAL_STATUS_BLOCKED, JOB_STATUS_DEAD, JOB_STATUS_PENDING,
    JOB_STATUS_RUNNING, NODE_STATUS_DOWN,
)

TABLES = ("nodes", "jobs", "evals", "allocs", "deployments", "node_pools",
          "scheduler_config", "job_versions", "acl_policies", "acl_tokens",
          "acl_roles", "root_keys", "variables", "scaling_policies",
          "scaling_events",
          "namespaces", "csi_volumes", "csi_plugins", "services")


def _delta_journal_cap() -> int:
    """Alloc-delta journal capacity (NOMAD_TPU_DELTA_JOURNAL, default
    128 entries = the ISSUE-6 fixed bound).  One entry per alloc-table
    write: a group-committed LP batch is ONE entry regardless of pair
    count, but high write fan-out (serial applier, client updates)
    wraps the journal and forces incremental-memo holders into
    wholesale rebuilds -- watch nomad.state.delta_journal_overflow."""
    import os
    try:
        return max(8, int(os.environ.get("NOMAD_TPU_DELTA_JOURNAL",
                                         "128")))
    except ValueError:
        return 128


class _DeltaAllocs:
    """Journal-patched snapshot alloc mapping (ISSUE 17): the previous
    snapshot's mapping advanced copy-on-write by the alloc-delta journal
    span, instead of rebuilt with a wholesale ``dict(store._allocs)``
    copy (~250K dict inserts per snapshot at north-star scale).

    ``base`` is a frozen plain dict shared with an earlier snapshot and
    is NEVER mutated; ``over`` holds inserted/replaced allocs; ``dead``
    tombstones ids deleted from base. Each advance copies the (bounded
    small) overlay, so chains never deepen past one level, and the store
    flattens back to a plain dict when the overlay outgrows its budget
    (StateStore._snapshot_allocs_locked). Iteration yields base order
    first, then overlay order -- replaced allocs move to the tail, which
    the snapshot read API tolerates (id-keyed lookups and unordered
    scans); the kill-switch path keeps exact dict-copy order."""

    __slots__ = ("_base", "_over", "_dead")

    def __init__(self, base: dict, over: dict, dead: set):
        self._base = base
        self._over = over
        self._dead = dead

    def get(self, key, default=None):
        v = self._over.get(key)
        if v is not None:
            return v
        if key in self._dead:
            return default
        return self._base.get(key, default)

    def __getitem__(self, key):
        v = self.get(key)
        if v is None:
            raise KeyError(key)
        return v

    def __contains__(self, key) -> bool:
        return (key in self._over
                or (key not in self._dead and key in self._base))

    def __len__(self) -> int:
        n = len(self._base) - len(self._dead)
        for k in self._over:
            if k in self._base:
                n -= 1
        return n + len(self._over)

    def __iter__(self):
        base, over, dead = self._base, self._over, self._dead
        for k in base:
            if k not in dead and k not in over:
                yield k
        yield from over

    def keys(self):
        return list(self)

    def values(self):
        base, over, dead = self._base, self._over, self._dead
        out = [v for k, v in base.items()
               if k not in dead and k not in over]
        out.extend(over.values())
        return out

    def items(self):
        base, over, dead = self._base, self._over, self._dead
        out = [(k, v) for k, v in base.items()
               if k not in dead and k not in over]
        out.extend(over.items())
        return out


class StateSnapshot:
    """An immutable point-in-time view (reference: state.StateSnapshot).

    Shares object references with the live store; safe because writes
    replace objects instead of mutating them.
    """

    def __init__(self, store: "StateStore"):
        with store._lock:
            self.index = store._index
            # node-table version: cache key for tensorized fleet tables
            # (tensor/pack.py pack_nodes_cached)
            self.node_table_index = store._table_index.get("nodes", 0)
            self._nodes = dict(store._nodes)
            self._jobs = dict(store._jobs)
            self._evals = dict(store._evals)
            self._allocs = store._snapshot_allocs_locked()
            self._deployments = dict(store._deployments)
            self._node_pools = dict(store._node_pools)
            self._scheduler_config = store._scheduler_config
            # live reference: the dense solver's fast packing path may
            # observe usage newer than this snapshot; safe because the
            # plan applier re-verifies every plan against latest state
            self.alloc_table = store.alloc_table
            self._store = store
            # secondary indexes: incremental copy-on-write. Snapshots are
            # immutable, so a new snapshot reuses the previous snapshot's
            # inner id-set copies for every key the store has not touched
            # since -- a full {k: dict(v)} walk is ~120K dict inserts at
            # 10K nodes and was a top-5 leaf in the headline e2e profile.
            prev = store._snap_prev
            if prev is None:
                by_node = {k: dict(v)
                           for k, v in store._allocs_by_node.items()}
                by_job = {k: dict(v)
                          for k, v in store._allocs_by_job.items()}
            else:
                pn, pj = prev
                by_node = dict(pn)
                for k in store._dirty_alloc_nodes:
                    src = store._allocs_by_node.get(k)
                    if src:
                        by_node[k] = dict(src)
                    else:
                        by_node.pop(k, None)
                by_job = dict(pj)
                for k in store._dirty_alloc_jobs:
                    src = store._allocs_by_job.get(k)
                    if src:
                        by_job[k] = dict(src)
                    else:
                        by_job.pop(k, None)
            store._dirty_alloc_nodes.clear()
            store._dirty_alloc_jobs.clear()
            store._snap_prev = (by_node, by_job)
            self._allocs_by_node = by_node
            self._allocs_by_job = by_job
            self._csi_volumes = dict(store._csi_volumes)
            self._csi_plugins = dict(store._csi_plugins)

    # -- read API shared with the live store ---------------------------------
    def latest_index(self) -> int:
        return self.index

    def node_by_id(self, node_id: str) -> Optional[Node]:
        return self._nodes.get(node_id)

    def nodes(self) -> List[Node]:
        return list(self._nodes.values())

    def ready_nodes_in_pool(self, pool: str = "all") -> List[Node]:
        """(reference: state_store.go ReadyNodesInDC / node pool
        filtering). Memoized per snapshot: the O(N) ready scan ran once
        per EVAL (a measured ~8ms/eval fixed cost at 10K nodes) while
        every eval of a barrier generation shares one snapshot. The
        memo also keeps the node-id tuple so pack_nodes_cached can key
        its matrix cache without rebuilding it per eval
        (nodes_pack_key)."""
        return self._ready_memoized(("pool", pool))[0]

    def _ready_memoized(self, key):
        memo = self.__dict__.setdefault("_ready_memo", {})
        ent = memo.get(key)
        if ent is None:
            kind = key[0]
            if kind == "pool":
                pool = key[1]
                out = []
                for n in self._nodes.values():
                    if not n.ready():
                        continue
                    if pool not in ("", "all") and n.node_pool != pool:
                        continue
                    out.append(n)
            else:                       # ("dcs", pool, frozenset(dcs))
                base = self._ready_memoized(("pool", key[1]))[0]
                dcs = key[2]
                out = (base if "*" in dcs else
                       [n for n in base if n.datacenter in dcs])
            ent = memo.setdefault(key, (out, tuple(n.id for n in out)))
            # id-keyed reverse map for nodes_pack_key: a single atomic
            # dict read (concurrent evals insert into the memo while
            # others look up; iterating it would race). The memo keeps
            # the list alive, so its id stays valid for this snapshot.
            self.__dict__.setdefault("_ready_by_id", {})[id(ent[0])] = \
                ent[1]
        return ent

    def ready_nodes_in_pool_dcs(self, pool: str, dcs: frozenset
                                ) -> List[Node]:
        """ready_nodes_in_pool + the job's datacenter filter
        (reference: readyNodesInDCsAndPool), memoized per snapshot so
        concurrent evals of one barrier generation share one list."""
        return self._ready_memoized(("dcs", pool, dcs))[0]

    def nodes_pack_key(self, nodes) -> object:
        """The cached node-id tuple for a list this snapshot's ready
        memo handed out (identity match), else None -- lets
        pack_nodes_cached skip the per-eval O(N) id-tuple rebuild."""
        by_id = self.__dict__.get("_ready_by_id")
        if by_id:
            return by_id.get(id(nodes))
        return None

    def job_by_id(self, namespace: str, job_id: str) -> Optional[Job]:
        return self._jobs.get((namespace, job_id))

    def jobs(self) -> List[Job]:
        return list(self._jobs.values())

    def eval_by_id(self, eval_id: str) -> Optional[Evaluation]:
        return self._evals.get(eval_id)

    def evals_by_job(self, namespace: str, job_id: str) -> List[Evaluation]:
        return [e for e in self._evals.values()
                if e.namespace == namespace and e.job_id == job_id]

    def alloc_by_id(self, alloc_id: str) -> Optional[Allocation]:
        return self._allocs.get(alloc_id)

    def allocs(self) -> List[Allocation]:
        return list(self._allocs.values())

    def allocs_by_node(self, node_id: str) -> List[Allocation]:
        return [self._allocs[i] for i in self._allocs_by_node.get(node_id, ())
                if i in self._allocs]

    def allocs_by_node_terminal(self, node_id: str,
                                terminal: bool) -> List[Allocation]:
        return [a for a in self.allocs_by_node(node_id)
                if a.terminal_status() == terminal]

    def allocs_by_job(self, namespace: str, job_id: str,
                      anyCreateIndex: bool = True) -> List[Allocation]:
        return [self._allocs[i]
                for i in self._allocs_by_job.get((namespace, job_id), ())
                if i in self._allocs]

    def allocs_by_eval(self, eval_id: str) -> List[Allocation]:
        return [a for a in self._allocs.values() if a.eval_id == eval_id]

    def deployment_by_id(self, deployment_id: str) -> Optional[Deployment]:
        return self._deployments.get(deployment_id)

    def latest_deployment_by_job(self, namespace: str,
                                 job_id: str) -> Optional[Deployment]:
        best = None
        for d in self._deployments.values():
            if d.namespace == namespace and d.job_id == job_id:
                if best is None or d.create_index > best.create_index:
                    best = d
        return best

    def deployments(self) -> List[Deployment]:
        return list(self._deployments.values())

    def node_pool_by_name(self, name: str) -> Optional[NodePool]:
        return self._node_pools.get(name)

    def scheduler_config(self) -> SchedulerConfiguration:
        return self._scheduler_config

    def csi_volume_by_id(self, namespace: str, vol_id: str):
        return self._csi_volumes.get((namespace, vol_id))

    def csi_volumes(self, namespace: Optional[str] = None):
        return sorted(
            (v for v in self._csi_volumes.values()
             if namespace in (None, "*", v.namespace)),
            key=lambda v: (v.namespace, v.id))

    def csi_plugin_by_id(self, plugin_id: str):
        return self._csi_plugins.get(plugin_id)

    def csi_plugins(self):
        return sorted(self._csi_plugins.values(), key=lambda p: p.id)


class StateStore:
    """The live, writable store. All writes go through raft in the reference
    (fsm.go:211 nomadFSM.Apply); here the FSM calls these methods directly
    under one lock, bumping the index exactly once per logical write."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._index = 1
        self._table_index: Dict[str, int] = {t: 1 for t in TABLES}
        self._nodes: Dict[str, Node] = {}
        self._jobs: Dict[Tuple[str, str], Job] = {}
        self._job_versions: Dict[Tuple[str, str, int], Job] = {}
        self._evals: Dict[str, Evaluation] = {}
        self._allocs: Dict[str, Allocation] = {}
        self._deployments: Dict[str, Deployment] = {}
        self._node_pools: Dict[str, NodePool] = {"default": NodePool(name="default"),
                                                 "all": NodePool(name="all")}
        self._scheduler_config = SchedulerConfiguration()
        # ACL tables (reference: state_store.go ACLPolicy/ACLToken regions)
        self._acl_policies: Dict[str, "ACLPolicy"] = {}
        self._acl_roles: Dict[str, "ACLRole"] = {}
        self._acl_tokens: Dict[str, "ACLToken"] = {}          # by accessor
        self._acl_tokens_by_secret: Dict[str, str] = {}       # secret->accessor
        self._acl_bootstrapped = False
        # keyring + secure variables (reference: state_store.go RootKeyMeta
        # and VariablesQuota regions; variables keyed (namespace, path))
        self._root_keys: Dict[str, "RootKey"] = {}
        self._variables: Dict[Tuple[str, str], "VariableEncrypted"] = {}
        # scaling (reference: state_store.go ScalingPolicies/ScalingEvents
        # regions; policies derived from jobs on UpsertJob)
        self._scaling_policies: Dict[str, ScalingPolicy] = {}
        self._scaling_events: Dict[Tuple[str, str], List[ScalingEvent]] = {}
        # namespaces; "default" always exists (reference: structs/namespace)
        self._namespaces: Dict[str, "Namespace"] = {
            "default": Namespace(name="default",
                                 description="Default shared namespace")}
        # CSI (reference: state_store.go CSIVolume/CSIPlugin regions;
        # plugins derived from node fingerprints)
        self._csi_volumes: Dict[Tuple[str, str], "CSIVolume"] = {}
        self._csi_plugins: Dict[str, "CSIPlugin"] = {}
        # native service catalog (reference: state_store.go
        # service_registration region), keyed by registration id
        self._services: Dict[str, "ServiceRegistration"] = {}
        # secondary indexes: insertion-ordered id sets (dict keys). Plain
        # lists made _insert_allocs_locked O(K^2) in a job's alloc count
        # (a membership scan per insert) -- 70ms of a 2000-alloc plan
        # commit was this scan.
        self._allocs_by_node: Dict[str, Dict[str, None]] = {}
        self._allocs_by_job: Dict[Tuple[str, str], Dict[str, None]] = {}
        # snapshot cache: one StateSnapshot build per index (any write
        # invalidates); _snap_prev/_dirty_* feed the incremental
        # secondary-index copies in StateSnapshot.__init__
        self._snap_cache: Optional[StateSnapshot] = None
        self._snap_prev = None
        self._dirty_alloc_nodes: set = set()
        self._dirty_alloc_jobs: set = set()
        # (alloc table index, mapping) of the last snapshot's alloc view:
        # the base the next snapshot delta-advances from (ISSUE 17,
        # native control plane; see _snapshot_allocs_locked)
        self._snap_alloc_prev: Optional[Tuple[int, object]] = None
        # watch support
        self._watch_cond = threading.Condition(self._lock)
        # bounded journal of alloc-level write deltas: (index, pairs)
        # where pairs is [(old_alloc|None, new_alloc|None), ...] or None
        # for writes with no structured delta. Lets incremental memo
        # holders (solver/service.py usage base) catch a stale fold up
        # to the current index instead of refolding (ISSUE 6). Capacity
        # is a knob (NOMAD_TPU_DELTA_JOURNAL): an LP-queue batch commits
        # thousands of pairs in one plan group, and a journal sized for
        # per-eval commits silently degrades every incremental-memo
        # consumer to wholesale rebuilds (counted in
        # nomad.state.delta_journal_overflow).
        from collections import deque as _deque
        self._alloc_deltas: "_deque" = _deque(
            maxlen=_delta_journal_cap())
        # quality observatory hook (server/quality.py): set by
        # QualityObservatory.attach, receives every write's tables +
        # delta pairs alongside the module-level cache hooks. None
        # (the NOMAD_TPU_QUALITY=0 default for unattached stores) is
        # the prior path bit-for-bit.
        self._quality_hook = None
        # tensor-resident alloc table (fed to the TPU solver's native
        # packing kernels; maintained incrementally on every write)
        self.alloc_table = AllocTable()

    # -- watch / blocking query ---------------------------------------------
    def latest_index(self) -> int:
        with self._lock:
            return self._index

    def table_index(self, *tables: str) -> int:
        with self._lock:
            return max(self._table_index.get(t, 0) for t in tables)

    def block_until(self, min_index: int, timeout: float = 5.0,
                    tables: Tuple[str, ...] = ()) -> int:
        """Wait until the (table) index passes min_index
        (reference: blockingRPC nomad/rpc.go:852). Returns current index."""
        deadline = None
        import time as _time
        deadline = _time.monotonic() + timeout
        with self._watch_cond:
            while True:
                cur = (self.table_index(*tables) if tables else self._index)
                if cur > min_index:
                    return self._index
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return self._index
                self._watch_cond.wait(remaining)

    def _bump(self, *tables: str, delta=None) -> int:
        """Advance the raft-style index for a logical write. ``delta``
        carries the write's alloc-level change set -- a list of
        (old_alloc_or_None, new_alloc_or_None) pairs -- when the caller
        knows it (plan commits, client updates, GC deletes); cache
        layers get it through ONE delta-aware notification instead of a
        bare "something changed", and the bounded journal below lets
        incremental memo holders catch a stale base up to the current
        index by applying the missed deltas instead of refolding."""
        if schedcheck._ACTIVE:
            # schedule-explorer interposition: every index bump is a
            # decision point (one module-attr read when off)
            schedcheck.yield_point("store._bump")
        self._index += 1
        for t in tables:
            self._table_index[t] = self._index
        self._snap_cache = None
        if "allocs" in tables:
            # journal entry even for delta=None writes: consumers learn
            # the span is NOT coverable by deltas and must refold
            self._alloc_deltas.append((self._index, delta))
        hook = self._quality_hook
        if hook is not None:
            hook(tables, self._index, delta)
        self._notify_write_hooks(tables, self._index, delta)
        self._watch_cond.notify_all()
        return self._index

    @staticmethod
    def _notify_write_hooks(tables, index: int, delta) -> None:
        """One delta-aware notification shared by every cache layer
        (solver const cache + host pack caches). Resolved via
        sys.modules so a store used without the solver stack never pays
        the (jax-importing) solver package import; getattr-guarded
        because sys.modules can hand back a PARTIALLY initialized module
        while another thread is mid-import (first eval racing a node
        registration burst) -- there is nothing to invalidate before
        the module finished loading anyway."""
        import sys as _sys
        for mod in ("nomad_tpu.solver.constcache", "nomad_tpu.tensor.pack"):
            m = _sys.modules.get(mod)
            hook = getattr(m, "note_table_write", None)
            if hook is not None:
                hook(tables, index, delta)

    def alloc_deltas_since(self, index: int, upto: Optional[int] = None):
        """(covered, pairs): every alloc-level (old, new) change pair
        recorded for writes in (index, upto] (upto None = current).
        ``covered`` is False when the journal doesn't reach back that
        far or a write in the span carried no structured delta -- the
        consumer must refold instead of applying deltas."""
        with self._lock:
            pairs = []
            hi = self._table_index.get("allocs", 0) if upto is None \
                else upto
            if not self._alloc_deltas:
                return (index >= self._table_index.get("allocs", 0)
                        or index >= hi), pairs
            oldest = self._alloc_deltas[0][0]
            if index < oldest - 1:
                # the journal wrapped past the consumer's base index: an
                # overflow-forced wholesale rebuild (raise
                # NOMAD_TPU_DELTA_JOURNAL if this counts up under load)
                from ..server.telemetry import metrics as _tm
                _tm.incr("nomad.state.delta_journal_overflow")
                return False, pairs
            for idx, delta in self._alloc_deltas:
                if idx <= index or idx > hi:
                    continue
                if delta is None:
                    return False, []
                pairs.extend(delta)
            return True, pairs

    def _snapshot_allocs_locked(self):
        """The alloc mapping for a snapshot under construction (caller
        holds the store lock). Native-CP path (``NOMAD_TPU_NATIVE_CP``,
        default on): delta-advance the previous snapshot's mapping by
        the journal span -- O(changed allocs) instead of the wholesale
        ~len(_allocs)-insert dict copy that dominated snapshot build at
        north-star scale. The wholesale rebuild stays as the
        journal-gap/overflow fallback AND, with the kill switch off, as
        the bit-for-bit oracle."""
        from .. import native
        if not native.native_cp_enabled():
            return dict(self._allocs)
        from ..server.telemetry import metrics as _tm
        idx = self._table_index.get("allocs", 0)
        prev = self._snap_alloc_prev
        if prev is not None:
            prev_idx, prev_map = prev
            if prev_idx == idx:
                # a write to another table invalidated the snapshot
                # cache without touching allocs: reuse the frozen map
                _tm.incr("nomad.native.snapshot_hits")
                return prev_map
            covered, pairs = self.alloc_deltas_since(prev_idx, upto=idx)
            if covered:
                if isinstance(prev_map, _DeltaAllocs):
                    base = prev_map._base
                    over = dict(prev_map._over)
                    dead = set(prev_map._dead)
                else:
                    base, over, dead = prev_map, {}, set()
                for old, new in pairs:
                    if new is not None:
                        over[new.id] = new
                        dead.discard(new.id)
                    elif old is not None:
                        over.pop(old.id, None)
                        if old.id in base:
                            dead.add(old.id)
                # flatten once the overlay outgrows its budget: lookup
                # and scan costs scale with the overlay, and a big
                # overlay means the next wholesale copy is cheap
                # relative to the churn that built it
                if len(over) + len(dead) <= max(1024, len(base) // 8):
                    view = _DeltaAllocs(base, over, dead)
                    self._snap_alloc_prev = (idx, view)
                    _tm.incr("nomad.native.snapshot_hits")
                    return view
        allocs = dict(self._allocs)
        self._snap_alloc_prev = (idx, allocs)
        _tm.incr("nomad.native.snapshot_fallbacks")
        return allocs

    def snapshot(self) -> StateSnapshot:
        with self._lock:
            if self._snap_cache is None:
                self._snap_cache = StateSnapshot(self)
            return self._snap_cache

    # -- nodes ---------------------------------------------------------------
    def upsert_node(self, node: Node) -> int:
        with self._lock:
            existing = self._nodes.get(node.id)
            if existing is not None:
                node.create_index = existing.create_index
                # re-registration must not clear operator-set drain or
                # eligibility state (reference: state_store.go UpsertNode
                # retains drain_strategy / scheduling_eligibility from the
                # existing node): clients re-register at runtime (server
                # restart recovery, fingerprint changes) with no knowledge
                # of server-side drains; eligibility only changes through
                # the drain/eligibility endpoints
                if node.drain_strategy is None:
                    if existing.drain_strategy is not None:
                        node.drain_strategy = existing.drain_strategy
                    if existing.scheduling_eligibility:
                        node.scheduling_eligibility = \
                            existing.scheduling_eligibility
            else:
                node.create_index = self._index + 1
            node.modify_index = self._index + 1
            if not node.computed_class:
                node.compute_class()
            self._nodes[node.id] = node
            self.alloc_table.register_node(node)
            idx = self._bump("nodes")
            # the recompute walks every node; skip it when this write
            # cannot change plugin state (no CSI fingerprints on the new
            # node and none aggregated fleet-wide) -- otherwise a 10K-node
            # registration burst is O(N^2)
            if node.csi_node_plugins or self._csi_plugins:
                self._recompute_csi_plugins_locked()
            return idx

    def delete_node(self, node_id: str) -> int:
        with self._lock:
            node = self._nodes.pop(node_id, None)
            idx = self._bump("nodes")
            if (node is not None and node.csi_node_plugins) \
                    or self._csi_plugins:
                self._recompute_csi_plugins_locked()
            return idx

    def update_node_status(self, node_id: str, status: str,
                           updated_at: float = 0.0) -> int:
        with self._lock:
            old = self._nodes.get(node_id)
            if old is None:
                raise KeyError(f"node {node_id} not found")
            import copy as _copy
            node = _copy.copy(old)
            node.status = status
            node.status_updated_at = updated_at
            node.modify_index = self._index + 1
            self._nodes[node_id] = node
            idx = self._bump("nodes")
            if node.csi_node_plugins or self._csi_plugins:
                self._recompute_csi_plugins_locked()
            return idx

    def update_node_eligibility(self, node_id: str, eligibility: str) -> int:
        with self._lock:
            old = self._nodes.get(node_id)
            if old is None:
                raise KeyError(f"node {node_id} not found")
            import copy as _copy
            node = _copy.copy(old)
            node.scheduling_eligibility = eligibility
            node.modify_index = self._index + 1
            self._nodes[node_id] = node
            idx = self._bump("nodes")
            if node.csi_node_plugins or self._csi_plugins:
                self._recompute_csi_plugins_locked()
            return idx

    def update_node_drain(self, node_id: str, drain_strategy,
                          mark_eligible: bool = False) -> int:
        with self._lock:
            old = self._nodes.get(node_id)
            if old is None:
                raise KeyError(f"node {node_id} not found")
            import copy as _copy
            from ..structs import NODE_SCHED_ELIGIBLE, NODE_SCHED_INELIGIBLE
            node = _copy.copy(old)
            node.drain_strategy = drain_strategy
            if drain_strategy is not None:
                node.scheduling_eligibility = NODE_SCHED_INELIGIBLE
            elif mark_eligible:
                node.scheduling_eligibility = NODE_SCHED_ELIGIBLE
            node.modify_index = self._index + 1
            self._nodes[node_id] = node
            idx = self._bump("nodes")
            if node.csi_node_plugins or self._csi_plugins:
                self._recompute_csi_plugins_locked()
            return idx

    # -- jobs ----------------------------------------------------------------
    def upsert_job(self, job: Job) -> int:
        with self._lock:
            key = (job.namespace, job.id)
            existing = self._jobs.get(key)
            if existing is not None:
                job.create_index = existing.create_index
                job.version = existing.version + 1
            else:
                job.create_index = self._index + 1
                job.version = 0
            job.modify_index = self._index + 1
            job.job_modify_index = self._index + 1
            if job.status not in (JOB_STATUS_DEAD,):
                job.status = JOB_STATUS_PENDING
            self._jobs[key] = job
            self._job_versions[(job.namespace, job.id, job.version)] = job
            self._update_job_scaling_policies_locked(job)
            return self._bump("jobs", "job_versions")

    def _update_job_scaling_policies_locked(self, job: Job) -> None:
        """Re-derive the job's scaling policies from its groups' scaling
        blocks (reference: state_store.go updateJobScalingPolicies)."""
        import hashlib
        keep = set()
        for tg in job.task_groups:
            # defensive: never let a malformed block break FSM apply --
            # validation belongs to admission (Server._validate_job)
            if not tg.scaling or not isinstance(tg.scaling, dict):
                continue
            target = {"Namespace": job.namespace, "Job": job.id,
                      "Group": tg.name}
            pid = hashlib.sha1(
                f"{job.namespace}\x1f{job.id}\x1f{tg.name}".encode()
            ).hexdigest()[:36]
            keep.add(pid)
            existing = self._scaling_policies.get(pid)
            try:
                lo = int(tg.scaling.get("min", 0) or 0)
                hi = int(tg.scaling.get("max", tg.count))
            except (TypeError, ValueError):
                continue
            pol = ScalingPolicy(
                id=pid, namespace=job.namespace, job_id=job.id,
                type=str(tg.scaling.get("type", "horizontal")),
                target=target,
                min=lo, max=hi,
                policy=dict(tg.scaling.get("policy") or {}),
                enabled=bool(tg.scaling.get("enabled", True)),
                create_index=(existing.create_index if existing
                              else self._index + 1),
                modify_index=self._index + 1)
            self._scaling_policies[pid] = pol
        for pid, pol in list(self._scaling_policies.items()):
            if (pol.namespace, pol.job_id) == (job.namespace, job.id) and \
                    pid not in keep:
                del self._scaling_policies[pid]
        self._table_index["scaling_policies"] = self._index + 1

    def update_job_status(self, namespace: str, job_id: str,
                          status: str) -> int:
        """Status-only update: no new job version (reference: the FSM's
        setJobStatus path, distinct from Job.Register's version bump)."""
        with self._lock:
            key = (namespace, job_id)
            existing = self._jobs.get(key)
            if existing is None:
                return self._index
            import copy as _copy
            job = _copy.copy(existing)
            job.status = status
            job.modify_index = self._index + 1
            self._jobs[key] = job
            self._job_versions[(namespace, job_id, job.version)] = job
            return self._bump("jobs")

    def delete_job(self, namespace: str, job_id: str) -> int:
        with self._lock:
            self._jobs.pop((namespace, job_id), None)
            for k in [k for k in self._job_versions
                      if k[0] == namespace and k[1] == job_id]:
                del self._job_versions[k]
            for pid, pol in list(self._scaling_policies.items()):
                if (pol.namespace, pol.job_id) == (namespace, job_id):
                    del self._scaling_policies[pid]
            self._scaling_events.pop((namespace, job_id), None)
            return self._bump("jobs", "job_versions", "scaling_policies")

    def job_version(self, namespace: str, job_id: str,
                    version: int) -> Optional[Job]:
        with self._lock:
            return self._job_versions.get((namespace, job_id, version))

    def job_versions_by_id(self, namespace: str, job_id: str) -> List[Job]:
        """All tracked versions, newest first (reference:
        state_store.go JobVersionsByID)."""
        with self._lock:
            versions = [v for (ns, jid, _), v in self._job_versions.items()
                        if (ns, jid) == (namespace, job_id)]
            return sorted(versions, key=lambda j: -j.version)

    def update_job_stability(self, namespace: str, job_id: str,
                             version: int, stable: bool) -> int:
        """(reference: state_store.go UpdateJobStability)"""
        with self._lock:
            job = self._job_versions.get((namespace, job_id, version))
            if job is None:
                return self._index
            import copy as _copy
            updated = _copy.copy(job)
            updated.stable = stable
            updated.modify_index = self._index + 1
            self._job_versions[(namespace, job_id, version)] = updated
            current = self._jobs.get((namespace, job_id))
            if current is not None and current.version == version:
                self._jobs[(namespace, job_id)] = updated
            return self._bump("jobs", "job_versions")

    # -- scaling -------------------------------------------------------------
    def scaling_policies(self, namespace: Optional[str] = None
                         ) -> List[ScalingPolicy]:
        with self._lock:
            return [p for p in self._scaling_policies.values()
                    if namespace is None or p.namespace == namespace]

    def scaling_policy_by_id(self, policy_id: str
                             ) -> Optional[ScalingPolicy]:
        with self._lock:
            return self._scaling_policies.get(policy_id)

    def scaling_policies_by_job(self, namespace: str, job_id: str
                                ) -> List[ScalingPolicy]:
        with self._lock:
            return [p for p in self._scaling_policies.values()
                    if (p.namespace, p.job_id) == (namespace, job_id)]

    def upsert_scaling_event(self, namespace: str, job_id: str,
                             event: ScalingEvent) -> int:
        """Append to the job's scaling audit trail, keeping the most recent
        entries (reference: state_store.go UpsertScalingEvent, bounded by
        structs.JobTrackedScalingEvents=20)."""
        with self._lock:
            events = self._scaling_events.setdefault((namespace, job_id), [])
            events.append(event)
            if len(events) > 20:
                del events[:-20]
            return self._bump("scaling_events")

    def scaling_events_by_job(self, namespace: str, job_id: str
                              ) -> List[ScalingEvent]:
        with self._lock:
            return list(self._scaling_events.get((namespace, job_id), []))

    # -- evals ---------------------------------------------------------------
    def upsert_evals(self, evals: List[Evaluation]) -> int:
        import time as _time
        now = _time.time()
        with self._lock:
            for ev in evals:
                existing = self._evals.get(ev.id)
                if existing is not None:
                    ev.create_index = existing.create_index
                    ev.create_time = existing.create_time
                else:
                    ev.create_index = self._index + 1
                    ev.create_time = now
                ev.modify_index = self._index + 1
                ev.modify_time = now
                self._evals[ev.id] = ev
                self._update_job_summary_status(ev)
            return self._bump("evals")

    def delete_evals(self, eval_ids: List[str]) -> int:
        with self._lock:
            for eid in eval_ids:
                self._evals.pop(eid, None)
            return self._bump("evals")

    def _update_job_summary_status(self, ev: Evaluation) -> None:
        # Blocked eval => job still pending work; minimal summary upkeep.
        pass

    # -- allocs --------------------------------------------------------------
    def upsert_allocs(self, allocs: List[Allocation]) -> int:
        with self._lock:
            pairs = self._insert_allocs_locked(allocs)
            return self._bump("allocs", delta=pairs)

    def _insert_allocs_locked(self, allocs: List[Allocation]) -> list:
        """Returns the write's (old_alloc_or_None, new_alloc) delta pairs
        for the _bump journal."""
        import time as _time
        now = _time.time()
        pairs = []
        for alloc in allocs:
            existing = self._allocs.get(alloc.id)
            if existing is not None:
                alloc.create_index = existing.create_index
                alloc.create_time = existing.create_time
            else:
                alloc.create_index = self._index + 1
                alloc.create_time = now
            alloc.modify_index = self._index + 1
            alloc.modify_time = now
            if alloc.job is None and existing is not None:
                alloc.job = existing.job
            self._allocs[alloc.id] = alloc
            pairs.append((existing, alloc))
            self._allocs_by_node.setdefault(alloc.node_id, {})[alloc.id] = None
            self._dirty_alloc_nodes.add(alloc.node_id)
            jk = (alloc.namespace, alloc.job_id)
            self._allocs_by_job.setdefault(jk, {})[alloc.id] = None
            self._dirty_alloc_jobs.add(jk)
        self.alloc_table.upsert_many(allocs)
        return pairs

    def update_allocs_from_client(self, allocs: List[Allocation]) -> int:
        """Client-side status updates (reference: Node.UpdateAlloc
        node_endpoint.go:1322 -> state UpdateAllocsFromClient)."""
        with self._lock:
            pairs = []
            for updated in allocs:
                existing = self._allocs.get(updated.id)
                if existing is None:
                    continue
                import copy as _copy
                alloc = _copy.copy(existing)
                alloc.client_status = updated.client_status
                alloc.client_description = updated.client_description
                alloc.task_states = dict(updated.task_states)
                alloc.network_status = updated.network_status
                if updated.deployment_status is not None:
                    alloc.deployment_status = updated.deployment_status
                if updated.client_terminal_time:
                    alloc.client_terminal_time = updated.client_terminal_time
                alloc.modify_index = self._index + 1
                import time as _time
                alloc.modify_time = _time.time()
                self._allocs[alloc.id] = alloc
                pairs.append((existing, alloc))
                self.alloc_table.upsert(alloc)
            return self._bump("allocs", delta=pairs)

    def update_alloc_desired_transition(self, alloc_ids: List[str],
                                        migrate: bool = True) -> int:
        """(reference: state AllocUpdateDesiredTransition, used by the
        drainer to request migrations)."""
        with self._lock:
            import copy as _copy
            from ..structs import DesiredTransition
            pairs = []
            for aid in alloc_ids:
                existing = self._allocs.get(aid)
                if existing is None:
                    continue
                alloc = _copy.copy(existing)
                alloc.desired_transition = DesiredTransition(migrate=migrate)
                alloc.modify_index = self._index + 1
                self._allocs[aid] = alloc
                pairs.append((existing, alloc))
            return self._bump("allocs", delta=pairs)

    def delete_allocs(self, alloc_ids: List[str]) -> int:
        with self._lock:
            pairs = []
            for aid in alloc_ids:
                a = self._allocs.pop(aid, None)
                if a is not None:
                    pairs.append((a, None))
                    ids = self._allocs_by_node.get(a.node_id)
                    if ids is not None:
                        ids.pop(aid, None)
                    self._dirty_alloc_nodes.add(a.node_id)
                    jk = (a.namespace, a.job_id)
                    jids = self._allocs_by_job.get(jk)
                    if jids is not None:
                        jids.pop(aid, None)
                    self._dirty_alloc_jobs.add(jk)
                self.alloc_table.remove(aid)
            return self._bump("allocs", delta=pairs)

    # -- deployments ---------------------------------------------------------
    def upsert_deployment(self, deployment: Deployment) -> int:
        with self._lock:
            self._upsert_deployment_locked(deployment)
            return self._index

    def upsert_deployment_cas(self, deployment: Deployment,
                              expected_modify_index: int) -> bool:
        """Compare-and-swap: commit only if the stored deployment's
        modify_index still matches (lost-update guard for the watcher)."""
        with self._lock:
            existing = self._deployments.get(deployment.id)
            if existing is not None and \
                    existing.modify_index != expected_modify_index:
                return False
            self._upsert_deployment_locked(deployment)
            return True

    def _upsert_deployment_locked(self, deployment: Deployment) -> None:
        existing = self._deployments.get(deployment.id)
        if existing is not None:
            deployment.create_index = existing.create_index
        else:
            deployment.create_index = self._index + 1
        deployment.modify_index = self._index + 1
        self._deployments[deployment.id] = deployment
        self._bump("deployments")

    def delete_deployment(self, deployment_id: str) -> int:
        with self._lock:
            self._deployments.pop(deployment_id, None)
            return self._bump("deployments")

    # -- node pools / config -------------------------------------------------
    def upsert_node_pool(self, pool: NodePool) -> int:
        with self._lock:
            existing = self._node_pools.get(pool.name)
            pool.create_index = (existing.create_index if existing
                                 else self._index + 1)
            pool.modify_index = self._index + 1
            self._node_pools[pool.name] = pool
            return self._bump("node_pools")

    def delete_node_pool(self, name: str) -> int:
        """Built-in pools are undeletable; the caller enforces emptiness
        (reference: node_pool_endpoint.go DeleteNodePools)."""
        with self._lock:
            if name in ("default", "all"):
                return self._index
            self._node_pools.pop(name, None)
            return self._bump("node_pools")

    def node_pools(self) -> List[NodePool]:
        with self._lock:
            return sorted(self._node_pools.values(), key=lambda p: p.name)

    # -- namespaces (reference: state_store.go Namespace region) -----------
    def upsert_namespace(self, namespace: "Namespace") -> int:
        with self._lock:
            existing = self._namespaces.get(namespace.name)
            namespace.create_index = (existing.create_index if existing
                                      else self._index + 1)
            namespace.modify_index = self._index + 1
            self._namespaces[namespace.name] = namespace
            return self._bump("namespaces")

    def delete_namespace(self, name: str) -> int:
        with self._lock:
            if name == "default":
                return self._index
            self._namespaces.pop(name, None)
            return self._bump("namespaces")

    def namespace_by_name(self, name: str) -> Optional["Namespace"]:
        with self._lock:
            return self._namespaces.get(name)

    def namespaces(self) -> List["Namespace"]:
        with self._lock:
            return sorted(self._namespaces.values(), key=lambda n: n.name)

    # -- CSI volumes + plugins (reference: state_store.go CSIVolume region,
    #    volumewatcher claim release) --------------------------------------
    def upsert_csi_volume(self, vol: "CSIVolume") -> int:
        with self._lock:
            key = (vol.namespace, vol.id)
            existing = self._csi_volumes.get(key)
            if existing is not None:
                vol.create_index = existing.create_index
                # claims survive re-registration
                vol.read_claims = dict(existing.read_claims)
                vol.write_claims = dict(existing.write_claims)
            else:
                vol.create_index = self._index + 1
            vol.modify_index = self._index + 1
            self._csi_volumes[key] = vol
            return self._bump("csi_volumes")

    def delete_csi_volume(self, namespace: str, vol_id: str) -> int:
        """Caller enforces no-claims; built to be idempotent."""
        with self._lock:
            self._csi_volumes.pop((namespace, vol_id), None)
            return self._bump("csi_volumes")

    def csi_volume_by_id(self, namespace: str, vol_id: str
                         ) -> Optional["CSIVolume"]:
        with self._lock:
            return self._csi_volumes.get((namespace, vol_id))

    def csi_volumes(self, namespace: Optional[str] = None
                    ) -> List["CSIVolume"]:
        with self._lock:
            return sorted(
                (v for v in self._csi_volumes.values()
                 if namespace in (None, "*", v.namespace)),
                key=lambda v: (v.namespace, v.id))

    def csi_volume_release(self, namespace: str, vol_id: str,
                           alloc_id: str) -> int:
        """Drop an alloc's claims (reference: CSIVolumeClaim w/ release
        state, driven by the volume watcher)."""
        with self._lock:
            vol = self._csi_volumes.get((namespace, vol_id))
            if vol is None:
                return self._index
            import copy as _copy
            nv = _copy.copy(vol)
            nv.read_claims = {k: c for k, c in vol.read_claims.items()
                              if k != alloc_id}
            nv.write_claims = {k: c for k, c in vol.write_claims.items()
                               if k != alloc_id}
            if (len(nv.read_claims), len(nv.write_claims)) == \
                    (len(vol.read_claims), len(vol.write_claims)):
                return self._index
            nv.modify_index = self._index + 1
            self._csi_volumes[(namespace, vol_id)] = nv
            return self._bump("csi_volumes")

    def _csi_claim_locked(self, alloc: Allocation) -> None:
        """Claim the CSI volumes an alloc's group requests; called from
        upsert_plan_results so claims replicate deterministically with the
        placement itself (reference: csi_hook + CSIVolume.Claim RPC)."""
        from ..structs.csi import CLAIM_READ, CLAIM_WRITE, CSIVolumeClaim
        job = alloc.job
        if job is None:
            return
        tg = job.lookup_task_group(alloc.task_group)
        if tg is None:
            return
        for req in (tg.volumes or {}).values():
            if req.type != "csi":
                continue
            source = req.source_for(alloc.name)
            vol = self._csi_volumes.get((job.namespace, source))
            if vol is None:
                continue
            import copy as _copy
            nv = _copy.copy(vol)
            nv.read_claims = dict(vol.read_claims)
            nv.write_claims = dict(vol.write_claims)
            claim = CSIVolumeClaim(
                alloc_id=alloc.id, node_id=alloc.node_id,
                mode=CLAIM_READ if req.read_only else CLAIM_WRITE)
            if req.read_only:
                nv.read_claims[alloc.id] = claim
            else:
                nv.write_claims[alloc.id] = claim
            nv.modify_index = self._index + 1
            self._csi_volumes[(job.namespace, source)] = nv
            self._table_index["csi_volumes"] = self._index + 1

    def _recompute_csi_plugins_locked(self) -> None:
        """Aggregate per-node fingerprints into fleet-wide plugin rows
        (reference: state_store.go updateNodeCSIPlugins)."""
        from ..structs.csi import CSIPlugin, plugin_healthy
        plugins: Dict[str, CSIPlugin] = {}
        for node in self._nodes.values():
            if not node.ready():
                continue
            for pid, info in (node.csi_node_plugins or {}).items():
                p = plugins.setdefault(pid, CSIPlugin(id=pid))
                if plugin_healthy(info):
                    p.nodes_healthy += 1
                    p.node_ids.append(node.id)
        self._csi_plugins = plugins
        self._table_index["csi_plugins"] = self._index

    def csi_plugins(self) -> List["CSIPlugin"]:
        with self._lock:
            return sorted(self._csi_plugins.values(), key=lambda p: p.id)

    def csi_plugin_by_id(self, plugin_id: str) -> Optional["CSIPlugin"]:
        with self._lock:
            return self._csi_plugins.get(plugin_id)

    # -- native service catalog (reference: state_store.go
    #    UpsertServiceRegistrations / DeleteServiceRegistrationByID) ------
    def upsert_service_registrations(
            self, regs: List["ServiceRegistration"]) -> int:
        with self._lock:
            for reg in regs:
                existing = self._services.get(reg.id)
                reg.create_index = (existing.create_index if existing
                                    else self._index + 1)
                reg.modify_index = self._index + 1
                self._services[reg.id] = reg
            return self._bump("services")

    def delete_service_registrations(self, reg_ids: List[str]) -> int:
        with self._lock:
            for rid in reg_ids:
                self._services.pop(rid, None)
            return self._bump("services")

    def delete_services_by_alloc(self, alloc_id: str) -> int:
        """All of one alloc's registrations at once (reference:
        DeleteServiceRegistrationByAllocID, the client-restart sweep)."""
        return self.delete_services_by_allocs([alloc_id])

    def delete_services_by_allocs(self, alloc_ids: List[str]) -> int:
        """Batch sweep: one pass, one index bump, one raft entry."""
        with self._lock:
            ids = set(alloc_ids)
            gone = [rid for rid, r in self._services.items()
                    if r.alloc_id in ids]
            for rid in gone:
                del self._services[rid]
            return self._bump("services") if gone else self._index

    def restore_from_snapshot(self, blob: dict) -> int:
        """Atomically replace ALL state with a snapshot's contents; a
        replicated write so every peer swaps identically (reference: raft
        snapshot install -> FSM Restore)."""
        from ..statecheck import mark_uncoverable
        from .restore import restore_state
        with self._lock:
            prior = self._index
            restore_state(self, blob)
            # indexes must stay monotonic for blocking-query watchers even
            # when restoring an older snapshot
            self._index = max(self._index, prior)
            # the restore replaces alloc state wholesale: its delta-less
            # journal entry is an EXPLICIT coverage gap (incremental
            # memo holders must refold), which the snapshot-isolation
            # sanitizer would otherwise flag as a silent one
            with mark_uncoverable("raft snapshot restore"):
                # nomadlint: waive=delta-carried -- wholesale restore:
                # no (old, new) pair set exists; the mark_uncoverable
                # scope makes the gap explicit to statecheck's runtime
                # journal-gap detector too
                return self._bump(*TABLES)

    def delete_services_by_node(self, node_id: str) -> int:
        """One-pass sweep of a dead node's registrations (reference:
        DeleteServiceRegistrationByNodeID)."""
        with self._lock:
            gone = [rid for rid, r in self._services.items()
                    if r.node_id == node_id]
            for rid in gone:
                del self._services[rid]
            return self._bump("services") if gone else self._index

    def service_registrations(self, namespace: Optional[str] = None
                              ) -> List["ServiceRegistration"]:
        with self._lock:
            return sorted(
                (s for s in self._services.values()
                 if namespace in (None, "*", s.namespace)),
                key=lambda s: (s.namespace, s.service_name, s.id))

    def services_by_name(self, namespace: str, name: str
                         ) -> List["ServiceRegistration"]:
        with self._lock:
            return sorted(
                (s for s in self._services.values()
                 if s.namespace == namespace and s.service_name == name),
                key=lambda s: s.id)

    # -- keyring + variables (reference: state_store.go UpsertRootKeyMeta,
    #    VarSet/VarGet/VarDelete with check-and-set semantics) -------------
    def upsert_root_key(self, key: "RootKey") -> int:
        with self._lock:
            existing = self._root_keys.get(key.key_id)
            key.create_index = (existing.create_index if existing
                                else self._index + 1)
            key.modify_index = self._index + 1
            self._root_keys[key.key_id] = key
            return self._bump("root_keys")

    def delete_root_key(self, key_id: str) -> int:
        with self._lock:
            self._root_keys.pop(key_id, None)
            return self._bump("root_keys")

    def root_key_by_id(self, key_id: str):
        with self._lock:
            return self._root_keys.get(key_id)

    def root_keys(self) -> List:
        with self._lock:
            return list(self._root_keys.values())

    def upsert_variable(self, var: "VariableEncrypted",
                        cas_index: Optional[int] = None):
        """Returns (ok, conflict_or_result). cas_index None = blind write;
        0 = create-only; N = modify_index must equal N
        (reference: VarSet CAS contract in nomad/variables_endpoint.go)."""
        with self._lock:
            key = (var.meta.namespace, var.meta.path)
            existing = self._variables.get(key)
            if cas_index is not None:
                current = existing.meta.modify_index if existing else 0
                if current != cas_index:
                    return False, existing
            import time as _time
            now = _time.time()
            if existing is not None:
                var.meta.create_index = existing.meta.create_index
                var.meta.create_time = existing.meta.create_time
            else:
                var.meta.create_index = self._index + 1
                var.meta.create_time = now
            var.meta.modify_index = self._index + 1
            var.meta.modify_time = now
            self._variables[key] = var
            self._bump("variables")
            return True, var

    def delete_variable(self, namespace: str, path: str,
                        cas_index: Optional[int] = None):
        with self._lock:
            key = (namespace, path)
            existing = self._variables.get(key)
            if cas_index is not None:
                current = existing.meta.modify_index if existing else 0
                if current != cas_index:
                    return False, existing
            if existing is not None:
                del self._variables[key]
                self._bump("variables")
            return True, existing

    def variable_by_path(self, namespace: str, path: str):
        with self._lock:
            return self._variables.get((namespace, path))

    def variables(self, namespace: Optional[str] = None,
                  prefix: str = "") -> List:
        with self._lock:
            return [v for (ns, path), v in sorted(self._variables.items())
                    if (namespace is None or ns == namespace)
                    and path.startswith(prefix)]

    # -- ACL tables (reference: state_store.go UpsertACLPolicies /
    #    UpsertACLTokens / BootstrapACLTokens regions) -----------------------
    def upsert_acl_policies(self, policies: List[ACLPolicy]) -> int:
        with self._lock:
            for p in policies:
                existing = self._acl_policies.get(p.name)
                p.create_index = (existing.create_index if existing
                                  else self._index + 1)
                p.modify_index = self._index + 1
                self._acl_policies[p.name] = p
            return self._bump("acl_policies")

    def delete_acl_policies(self, names: List[str]) -> int:
        with self._lock:
            for name in names:
                self._acl_policies.pop(name, None)
            return self._bump("acl_policies")

    def upsert_acl_roles(self, roles: List["ACLRole"]) -> int:
        with self._lock:
            for r in roles:
                existing = self._acl_roles.get(r.name)
                r.create_index = (existing.create_index if existing
                                  else self._index + 1)
                r.modify_index = self._index + 1
                self._acl_roles[r.name] = r
            return self._bump("acl_roles")

    def delete_acl_roles(self, names: List[str]) -> int:
        with self._lock:
            for name in names:
                self._acl_roles.pop(name, None)
            return self._bump("acl_roles")

    def acl_role_by_name(self, name: str) -> Optional["ACLRole"]:
        with self._lock:
            return self._acl_roles.get(name)

    def acl_roles(self) -> List["ACLRole"]:
        with self._lock:
            return list(self._acl_roles.values())

    def acl_policy_by_name(self, name: str) -> Optional[ACLPolicy]:
        with self._lock:
            return self._acl_policies.get(name)

    def acl_policies(self) -> List[ACLPolicy]:
        with self._lock:
            return list(self._acl_policies.values())

    def upsert_acl_tokens(self, tokens: List[ACLToken]) -> int:
        with self._lock:
            for t in tokens:
                existing = self._acl_tokens.get(t.accessor_id)
                t.create_index = (existing.create_index if existing
                                  else self._index + 1)
                t.modify_index = self._index + 1
                if existing is not None:
                    self._acl_tokens_by_secret.pop(existing.secret_id, None)
                self._acl_tokens[t.accessor_id] = t
                self._acl_tokens_by_secret[t.secret_id] = t.accessor_id
            return self._bump("acl_tokens")

    def delete_acl_tokens(self, accessor_ids: List[str]) -> int:
        with self._lock:
            for acc in accessor_ids:
                t = self._acl_tokens.pop(acc, None)
                if t is not None:
                    self._acl_tokens_by_secret.pop(t.secret_id, None)
            return self._bump("acl_tokens")

    def acl_token_by_accessor(self, accessor_id: str) -> Optional[ACLToken]:
        with self._lock:
            return self._acl_tokens.get(accessor_id)

    def acl_token_by_secret(self, secret_id: str) -> Optional[ACLToken]:
        with self._lock:
            acc = self._acl_tokens_by_secret.get(secret_id)
            return self._acl_tokens.get(acc) if acc else None

    def acl_tokens(self) -> List[ACLToken]:
        with self._lock:
            return list(self._acl_tokens.values())

    def bootstrap_acl_token(self, token: ACLToken) -> bool:
        """One-shot management bootstrap (reference: state_store.go
        BootstrapACLTokens -- guarded by the acl-token-bootstrap index).
        Deleting every management token re-opens bootstrap (the escape
        hatch the reference provides via bootstrap-reset)."""
        with self._lock:
            have_mgmt = any(t.type == ACL_TOKEN_TYPE_MANAGEMENT
                            and not t.is_expired()
                            for t in self._acl_tokens.values())
            if self._acl_bootstrapped and have_mgmt:
                return False
            self._acl_bootstrapped = True
            token.create_index = self._index + 1
            token.modify_index = self._index + 1
            self._acl_tokens[token.accessor_id] = token
            self._acl_tokens_by_secret[token.secret_id] = token.accessor_id
            self._bump("acl_tokens")
            return True

    def acl_bootstrapped(self) -> bool:
        with self._lock:
            return self._acl_bootstrapped

    def set_scheduler_config(self, cfg: SchedulerConfiguration) -> int:
        with self._lock:
            cfg.modify_index = self._index + 1
            self._scheduler_config = cfg
            return self._bump("scheduler_config")

    def scheduler_config(self) -> SchedulerConfiguration:
        with self._lock:
            return self._scheduler_config

    # -- plan application ----------------------------------------------------
    def _stage_plan_result_locked(self, result: PlanResult,
                                  eval_updates: Optional[List[Evaluation]]
                                  ) -> Tuple[List[Allocation],
                                             List[Allocation]]:
        """Apply one plan result's dict/object writes (stop merges,
        deployments, eval updates) WITHOUT touching the tensor table or
        secondary indexes, which the caller batches across plans. Returns
        (merged_stops, placements, delta_pairs) -- the first two for
        those deferred columnar writes, the pairs for the _bump journal.
        Lock held; no index bump here."""
        stops: List[Allocation] = []
        for allocs in result.node_update.values():
            stops.extend(allocs)
        for allocs in result.node_preemptions.values():
            stops.extend(allocs)
        placements: List[Allocation] = []
        for allocs in result.node_allocation.values():
            placements.extend(allocs)

        # Stops/preemptions update desired status on existing allocs
        import copy as _copy
        import time as _time
        merged = []
        pairs = []
        for stop in stops:
            existing = self._allocs.get(stop.id)
            if existing is None:
                continue
            alloc = _copy.copy(existing)
            alloc.desired_status = stop.desired_status
            alloc.desired_description = stop.desired_description
            alloc.preempted_by_allocation = stop.preempted_by_allocation
            if stop.client_status:
                alloc.client_status = stop.client_status
            if stop.followup_eval_id:
                alloc.followup_eval_id = stop.followup_eval_id
            alloc.modify_index = self._index + 1
            alloc.modify_time = _time.time()
            self._allocs[alloc.id] = alloc
            merged.append(alloc)
            pairs.append((existing, alloc))

        if result.deployment is not None:
            d = result.deployment
            existing_d = self._deployments.get(d.id)
            if existing_d is not None:
                d.create_index = existing_d.create_index
            else:
                d.create_index = self._index + 1
            d.modify_index = self._index + 1
            self._deployments[d.id] = d
        for du in result.deployment_updates:
            d = self._deployments.get(du.deployment_id)
            if d is not None:
                nd = _copy.copy(d)
                nd.status = du.status
                nd.status_description = du.status_description
                nd.modify_index = self._index + 1
                self._deployments[nd.id] = nd

        if eval_updates:
            for ev in eval_updates:
                ev.modify_index = self._index + 1
                self._evals[ev.id] = ev
        return merged, placements, pairs

    def upsert_plan_results(self, result: PlanResult,
                            eval_updates: Optional[List[Evaluation]] = None
                            ) -> int:
        """Commit a verified plan in one logical raft write
        (reference: state_store.go:382 UpsertPlanResults, applied by the FSM
        for ApplyPlanResultsRequestType)."""
        with self._lock:
            merged, placements, pairs = self._stage_plan_result_locked(
                result, eval_updates)
            # refresh the tensor rows (batched): the allocs just became
            # server-terminal, and the verify fast path's live_strict
            # column mirrors the applier's AllocsByNodeTerminal(false)
            # filter -- a stale 1 here overcounts usage on this node
            # until the client acks, which can fast-reject plans the
            # authoritative python check would accept
            # (tests/test_verify_fold.py pins this)
            self.alloc_table.upsert_many(merged)

            pairs.extend(self._insert_allocs_locked(placements))
            if self._csi_volumes:
                for alloc in placements:
                    self._csi_claim_locked(alloc)

            idx = self._bump("allocs", "deployments", "evals",
                             delta=pairs)
            result.alloc_index = idx
            return idx

    def apply_plan_results_batch(
            self, entries: List[Tuple[PlanResult,
                                      Optional[List[Evaluation]]]]
            ) -> Tuple[int, List[Optional[BaseException]]]:
        """Group commit (the WAL/raft batched-apply analog): N verified
        plan results land as ONE store transaction -- one lock
        acquisition, one raft-style index bump, one snapshot
        invalidation, and ONE columnar pass through
        ``AllocTable.upsert_many`` for the whole batch's stop merges and
        placements instead of one per plan.

        A plan whose staging raises (the ``plan.commit`` chaos point
        fires BEFORE its writes) is skipped -- the batch splits around
        it: surviving plans still commit exactly once, and the failing
        plan's exception rides the returned per-entry outcome list
        (None = committed)."""
        from ..faultinject import faults
        if schedcheck._ACTIVE:
            # schedule-explorer interposition: a batch commit is the
            # write-skew decision point ROADMAP-2's N workers multiply
            schedcheck.yield_point("store.apply_batch")
        with self._lock:
            outcomes: List[Optional[BaseException]] = []
            merged_all: List[Allocation] = []
            placements_all: List[Allocation] = []
            pairs_all: list = []
            staged: List[Tuple[PlanResult, List[Allocation]]] = []
            for result, eval_updates in entries:
                try:
                    faults.fire("plan.commit")
                    merged, placements, pairs = \
                        self._stage_plan_result_locked(result, eval_updates)
                except BaseException as e:  # noqa: BLE001 -- split batch
                    outcomes.append(e)
                    continue
                merged_all.extend(merged)
                placements_all.extend(placements)
                pairs_all.extend(pairs)
                staged.append((result, placements))
                outcomes.append(None)
            self.alloc_table.upsert_many(merged_all)
            pairs_all.extend(self._insert_allocs_locked(placements_all))
            if self._csi_volumes:
                for _, placements in staged:
                    for alloc in placements:
                        self._csi_claim_locked(alloc)
            idx = self._bump("allocs", "deployments", "evals",
                             delta=pairs_all)
            for result, _ in staged:
                result.alloc_index = idx
            return idx, outcomes

    def quality_usage_by_node(self) -> Dict[str, tuple]:
        """Per-node-id live usage served from the alloc table's
        incrementally-maintained fold columns, under the store lock --
        an independent accounting the quality layer's churn parity test
        triangulates against (delta-journal dict vs wholesale store
        fold vs this tensor-table fold)."""
        with self._lock:
            return self.alloc_table.usage_by_node()

    def preallocate_allocs(self, capacity: int) -> None:
        """Grow the tensor-resident alloc table to ``capacity`` rows in
        one resize, under the store lock (a north-star-scale bench run
        otherwise pays ~11 doubling copies of every column mid-commit).
        This is the sanctioned route -- callers must not reach through
        ``store.alloc_table`` directly (no-direct-table-write)."""
        with self._lock:
            self.alloc_table.preallocate(capacity)

    def compact_alloc_table(self, min_free: int = 4096,
                            free_ratio: float = 0.5):
        """Compact the tensor-resident alloc table once freed rows
        dominate: GC'd terminal allocs leave free rows behind, and under
        sustained churn those would otherwise pin peak-row-count RSS for
        the process lifetime. Compacts only when the free-row count
        exceeds BOTH ``min_free`` and ``free_ratio`` of the row span
        (small fleets never pay the copy). Returns the compaction stats
        dict, or None when below the watermark."""
        with self._lock:
            t = self.alloc_table
            if t.free_rows < min_free or \
                    t.free_rows < free_ratio * max(1, t.n_rows):
                return None
            return t.compact()

    # -- snapshot passthrough reads (so StateStore satisfies the scheduler's
    #    State interface directly in tests) --------------------------------
    def node_by_id(self, node_id):
        with self._lock:
            return self._nodes.get(node_id)

    def nodes(self):
        with self._lock:
            return list(self._nodes.values())

    def ready_nodes_in_pool(self, pool: str = "all"):
        return self.snapshot().ready_nodes_in_pool(pool)

    def ready_nodes_in_pool_dcs(self, pool: str, dcs: frozenset):
        return self.snapshot().ready_nodes_in_pool_dcs(pool, dcs)

    def nodes_pack_key(self, nodes):
        return self.snapshot().nodes_pack_key(nodes)

    def job_by_id(self, namespace, job_id):
        with self._lock:
            return self._jobs.get((namespace, job_id))

    def jobs(self):
        with self._lock:
            return list(self._jobs.values())

    def eval_by_id(self, eval_id):
        with self._lock:
            return self._evals.get(eval_id)

    def evals(self):
        with self._lock:
            return list(self._evals.values())

    def evals_by_job(self, namespace, job_id):
        with self._lock:
            return [e for e in self._evals.values()
                    if e.namespace == namespace and e.job_id == job_id]

    def alloc_by_id(self, alloc_id):
        with self._lock:
            return self._allocs.get(alloc_id)

    def allocs(self):
        with self._lock:
            return list(self._allocs.values())

    def allocs_by_node(self, node_id):
        with self._lock:
            return [self._allocs[i]
                    for i in self._allocs_by_node.get(node_id, ())
                    if i in self._allocs]

    def allocs_by_job(self, namespace, job_id, anyCreateIndex=True):
        with self._lock:
            return [self._allocs[i]
                    for i in self._allocs_by_job.get((namespace, job_id), ())
                    if i in self._allocs]

    def num_allocs_by_job(self, namespace, job_id) -> int:
        """O(1) alloc count off the secondary index (any status).
        Monitoring loops that only need a progress number must not pay
        the allocs_by_job object-list materialization per poll."""
        with self._lock:
            return len(self._allocs_by_job.get((namespace, job_id), ()))

    def allocs_by_eval(self, eval_id):
        with self._lock:
            return [a for a in self._allocs.values() if a.eval_id == eval_id]

    def deployment_by_id(self, deployment_id):
        with self._lock:
            return self._deployments.get(deployment_id)

    def latest_deployment_by_job(self, namespace, job_id):
        return self.snapshot().latest_deployment_by_job(namespace, job_id)

    def deployments(self):
        with self._lock:
            return list(self._deployments.values())

    def node_pool_by_name(self, name):
        with self._lock:
            return self._node_pools.get(name)
