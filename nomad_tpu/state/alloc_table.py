"""Incrementally-maintained flat allocation table: the tensor-resident
half of the state store.

Every alloc write updates fixed-width numpy rows (node slot, cpu, mem,
disk, liveness, job/tg hashes, ports), so the TPU solver's marshalling
step is a single native fold over the table (nomad_tpu/native.py
nt_pack_usage) instead of an O(nodes x allocs) Python walk per eval --
the "packed int32 tensors" marshalling of the north star maintained
incrementally at write time.
"""
from __future__ import annotations

import hashlib
import os
import threading
from functools import lru_cache
from typing import Dict, Optional

import numpy as np

from .. import native

MAX_PORTS = native.MAX_PORTS_PER_ALLOC


def pack_delta_enabled() -> bool:
    """Incremental fold maintenance (ISSUE 6): every alloc write adjusts
    the resident per-slot usage/verify folds in place instead of
    invalidating them wholesale, so sustained churn pays O(write) rather
    than an O(rows) refold per table version. ``NOMAD_TPU_PACK_DELTA=0``
    is the kill switch restoring the wholesale-invalidation path
    bit-for-bit (test-gated)."""
    return os.environ.get("NOMAD_TPU_PACK_DELTA", "1") != "0"


@lru_cache(maxsize=65536)
def stable_hash(*parts: str) -> int:
    # memoized: the key space is (namespace, job[, tg]) tuples -- small --
    # and a 2000-alloc plan commit was spending a third of its time
    # re-hashing the same job key per alloc
    h = hashlib.blake2b(digest_size=8)
    for p in parts:
        h.update(p.encode())
        h.update(b"\0")
    return int.from_bytes(h.digest(), "little")


class AllocTable:
    """Guarded by the owning StateStore's lock; all mutators are called
    with that lock held."""

    def __init__(self, initial_capacity: int = 1024):
        cap = initial_capacity
        self._row_of: Dict[str, int] = {}
        self._free: list = []
        # bumped on every mutation: packers cache fold results per
        # version (32 lanes of one barrier generation fold identically)
        self.version = 0
        self.n_rows = 0
        self._cap = cap
        self.node_slot = np.full(cap, -1, dtype=np.int32)
        self.cpu = np.zeros(cap, dtype=np.float64)
        self.mem = np.zeros(cap, dtype=np.float64)
        self.disk = np.zeros(cap, dtype=np.float64)
        self.live = np.zeros(cap, dtype=np.uint8)
        # live by the APPLIER's filter (terminal_status: desired
        # stop/evict OR client-terminal), vs `live` which is the
        # scheduler's filter (client-terminal only, ProposedAllocs)
        self.live_strict = np.zeros(cap, dtype=np.uint8)
        # any ports/networks/reserved-cores/devices on the alloc: nodes
        # carrying such rows need the full python fit walk in the plan
        # applier (the native kernel models cpu/mem/disk only)
        self.special = np.zeros(cap, dtype=np.uint8)
        self.job_hash = np.zeros(cap, dtype=np.uint64)
        self.jobtg_hash = np.zeros(cap, dtype=np.uint64)
        self.ports = np.full((cap, MAX_PORTS), -1, dtype=np.int32)
        self.rows_with_ports = 0
        self._overflow_rows: set = set()
        # node axis
        self._slot_of_node: Dict[str, int] = {}
        self.n_nodes = 0
        self._node_cap = 256
        self.dyn_lo = np.full(self._node_cap, 20000, dtype=np.int32)
        self.dyn_hi = np.full(self._node_cap, 32000, dtype=np.int32)
        # verify-fold memo: one vectorized per-slot usage fold per table
        # VERSION, shared by every plan the applier verifies between two
        # commits (a batch of 32 plans used to pay 32 full-table folds).
        # Only used on the NOMAD_TPU_PACK_DELTA=0 kill-switch path; with
        # deltas on, _fold_inc below is maintained in place instead.
        self._verify_fold_cache: Optional[tuple] = None
        # incremental per-slot fold columns (built lazily on first use,
        # then adjusted by every upsert/remove): uc/um/ud under the
        # scheduler's `live` filter (serves pack()'s non-port lanes),
        # vc/vm/vd/vspec under the applier's `live_strict` filter
        # (serves _fold_verify_all). vspec is a COUNT of live special
        # rows per slot (reversible, unlike the boolean OR).
        self._fold_inc: Optional[dict] = None

    # ------------------------------------------------------------------
    def register_node(self, node) -> int:
        self.version += 1    # dyn ranges/slots feed folds too
        slot = self._slot_of_node.get(node.id)
        if slot is None:
            if self.n_nodes == self._node_cap:
                grow = self._node_cap
                self._node_cap *= 2
                self.dyn_lo = np.resize(self.dyn_lo, self._node_cap)
                self.dyn_hi = np.resize(self.dyn_hi, self._node_cap)
                inc = self._fold_inc
                if inc is not None:
                    # new slots carry zero usage by definition
                    for k, arr in inc.items():
                        inc[k] = np.concatenate(
                            [arr, np.zeros(grow, dtype=arr.dtype)])
            slot = self.n_nodes
            self._slot_of_node[node.id] = slot
            self.n_nodes += 1
        self.dyn_lo[slot] = node.node_resources.min_dynamic_port
        self.dyn_hi[slot] = node.node_resources.max_dynamic_port
        return slot

    # -- incremental fold maintenance (NOMAD_TPU_PACK_DELTA) ------------
    def _fold_inc_build(self) -> dict:
        """Full recount into the per-slot incremental fold columns; the
        ground truth every delta adjustment must stay equal to
        (fold_parity_mismatch gates that in tests and the churn bench)."""
        cap = self._node_cap
        inc = {
            "uc": np.zeros(cap), "um": np.zeros(cap), "ud": np.zeros(cap),
            "vc": np.zeros(cap), "vm": np.zeros(cap), "vd": np.zeros(cap),
            "vspec": np.zeros(cap, dtype=np.int64),
        }
        n = self.n_rows
        if n:
            slots = self.node_slot[:n]
            ok = slots >= 0
            live = (self.live[:n] > 0) & ok
            m = slots[live]
            np.add.at(inc["uc"], m, self.cpu[:n][live])
            np.add.at(inc["um"], m, self.mem[:n][live])
            np.add.at(inc["ud"], m, self.disk[:n][live])
            lives = (self.live_strict[:n] > 0) & ok
            ms = slots[lives]
            np.add.at(inc["vc"], ms, self.cpu[:n][lives])
            np.add.at(inc["vm"], ms, self.mem[:n][lives])
            np.add.at(inc["vd"], ms, self.disk[:n][lives])
            np.add.at(inc["vspec"],
                      slots[lives & (self.special[:n] > 0)], 1)
        self._fold_inc = inc
        return inc

    def _fold_inc_get(self) -> Optional[dict]:
        if not pack_delta_enabled():
            return None
        inc = self._fold_inc
        if inc is None:
            inc = self._fold_inc_build()
        return inc

    def _fold_inc_row(self, row: int, sign: int) -> None:
        """Adjust the incremental fold by one row's CURRENT column values
        (sign -1 before overwriting/removing a row, +1 after writing)."""
        inc = self._fold_inc
        slot = int(self.node_slot[row])
        if slot < 0:
            return
        c, m, d = self.cpu[row], self.mem[row], self.disk[row]
        if self.live[row]:
            inc["uc"][slot] += sign * c
            inc["um"][slot] += sign * m
            inc["ud"][slot] += sign * d
        if self.live_strict[row]:
            inc["vc"][slot] += sign * c
            inc["vm"][slot] += sign * m
            inc["vd"][slot] += sign * d
            if self.special[row]:
                inc["vspec"][slot] += sign

    def _fold_inc_rows(self, rows: np.ndarray, sign: int) -> None:
        """Vectorized _fold_inc_row over a row-index array."""
        inc = self._fold_inc
        if inc is None or not len(rows):
            return
        slots = self.node_slot[rows]
        ok = slots >= 0
        r, s = rows[ok], slots[ok]
        if not len(r):
            return
        live = self.live[r] > 0
        np.add.at(inc["uc"], s[live], sign * self.cpu[r][live])
        np.add.at(inc["um"], s[live], sign * self.mem[r][live])
        np.add.at(inc["ud"], s[live], sign * self.disk[r][live])
        lives = self.live_strict[r] > 0
        np.add.at(inc["vc"], s[lives], sign * self.cpu[r][lives])
        np.add.at(inc["vm"], s[lives], sign * self.mem[r][lives])
        np.add.at(inc["vd"], s[lives], sign * self.disk[r][lives])
        spec = lives & (self.special[r] > 0)
        np.add.at(inc["vspec"], s[spec], sign)

    def fold_parity_mismatch(self, atol: float = 1e-6) -> int:
        """Parity gate for the delta path: compare the incrementally
        maintained fold against a fresh full recount; returns the number
        of mismatching slots (0 = parity). The fresh recount replaces
        the resident fold, so a detected drift also self-heals."""
        saved = self._fold_inc
        if saved is None:
            return 0
        fresh = self._fold_inc_build()      # re-assigns self._fold_inc
        n = self.n_nodes
        bad = np.zeros(n, dtype=bool)
        for k in ("uc", "um", "ud", "vc", "vm", "vd"):
            bad |= np.abs(saved[k][:n] - fresh[k][:n]) > atol
        bad |= (saved["vspec"][:n] > 0) != (fresh["vspec"][:n] > 0)
        return int(bad.sum())

    def node_slot_of(self, node_id: str) -> int:
        return self._slot_of_node.get(node_id, -1)

    def usage_by_node(self) -> Dict[str, tuple]:
        """Per-node-id (used_cpu, used_mem, used_disk) under the
        scheduler's `live` filter, served from the incremental fold
        columns (built on demand).  Caller holds the owning store's
        lock.  On the NOMAD_TPU_PACK_DELTA=0 kill-switch path the fold
        is computed fresh and NOT retained, so the wholesale-
        invalidation write path stays bit-for-bit untouched."""
        inc = self._fold_inc_get()
        transient = inc is None
        if transient:
            inc = self._fold_inc_build()
            self._fold_inc = None
        out = {}
        for nid, slot in self._slot_of_node.items():
            out[nid] = (float(inc["uc"][slot]), float(inc["um"][slot]),
                        float(inc["ud"][slot]))
        return out

    # ------------------------------------------------------------------
    def preallocate(self, capacity: int) -> None:
        """Grow the row arrays to ``capacity`` in ONE resize. A 2M-alloc
        run otherwise pays ~11 doubling copies of every column (the ports
        matrix alone is capacity x MAX_PORTS int32) while holding the
        store lock."""
        while self._cap < capacity:
            self._grow()

    def _grow(self) -> None:
        self._cap *= 2
        for name in ("node_slot", "cpu", "mem", "disk", "live",
                     "live_strict", "special",
                     "job_hash", "jobtg_hash"):
            arr = getattr(self, name)
            setattr(self, name, np.resize(arr, self._cap))
        new_ports = np.full((self._cap, MAX_PORTS), -1, dtype=np.int32)
        new_ports[:self.ports.shape[0]] = self.ports
        self.ports = new_ports

    def upsert(self, alloc) -> None:
        self.version += 1
        row = self._row_of.get(alloc.id)
        existed = row is not None
        if row is None:
            if self._free:
                row = self._free.pop()
            else:
                if self.n_rows == self._cap:
                    self._grow()
                row = self.n_rows
                self.n_rows += 1
            self._row_of[alloc.id] = row
        if existed and self._fold_inc is not None:
            # retract the row's old contribution before overwriting
            # (fresh/freed rows contribute nothing: remove() zeroes them)
            self._fold_inc_row(row, -1)
        cr = alloc.allocated_resources.comparable()
        self.node_slot[row] = self._slot_of_node.get(alloc.node_id, -1)
        self.cpu[row] = cr.cpu_shares
        self.mem[row] = cr.memory_mb
        self.disk[row] = cr.disk_mb
        self.live[row] = 0 if alloc.client_terminal_status() else 1
        self.live_strict[row] = 0 if alloc.terminal_status() else 1
        self.special[row] = \
            1 if alloc.allocated_resources.has_special_dimensions() else 0
        self.job_hash[row] = stable_hash(alloc.namespace, alloc.job_id)
        self.jobtg_hash[row] = stable_hash(alloc.namespace, alloc.job_id,
                                           alloc.task_group)
        if self._fold_inc is not None:
            self._fold_inc_row(row, +1)
        had_ports = self.ports[row, 0] >= 0
        had_overflow = row in self._overflow_rows
        self.ports[row, :] = -1
        ports = alloc.allocated_resources.all_ports()
        for pi, value in enumerate(ports[:MAX_PORTS]):
            self.ports[row, pi] = value
        if len(ports) > MAX_PORTS:
            # row can't represent all ports: the solver service must fall
            # back to the exact per-node fold while any overflow exists
            self._overflow_rows.add(row)
        elif had_overflow:
            self._overflow_rows.discard(row)
        has_ports = bool(ports)
        if has_ports and not had_ports:
            self.rows_with_ports += 1
        elif had_ports and not has_ports:
            self.rows_with_ports -= 1

    def upsert_many(self, allocs) -> None:
        """Batch upsert: the per-alloc path pays ~15 scalar numpy writes
        each (~10us/alloc -- ~20ms per 2000-alloc plan commit under the
        store lock); batching turns the columns into one vectorized
        assignment apiece. Falls back to the scalar path when a batch
        repeats an alloc id (fancy-index write order would be
        unspecified) -- plans never do, but correctness must not depend
        on it."""
        if len(allocs) < 8:
            for a in allocs:
                self.upsert(a)
            return
        ids = [a.id for a in allocs]
        if len(set(ids)) != len(ids):
            for a in allocs:
                self.upsert(a)
            return
        # derive EVERYTHING before the first state mutation: a raising
        # alloc mid-batch must not leave reserved-but-unwritten rows
        # (stale resized data would fold phantom usage)
        # batches routinely share AllocatedResources objects across
        # allocs of one task group (prebuilt TPU-path resources), so
        # memoize the derived views by object identity -- the `allocs`
        # list pins every object alive for the memo's whole lifetime
        _derived: dict = {}
        crs = []
        all_ports = []
        special = []
        for a in allocs:
            ar = a.allocated_resources
            got = _derived.get(id(ar))
            if got is None:
                got = (ar.comparable(), ar.all_ports(),
                       1 if ar.has_special_dimensions() else 0)
                _derived[id(ar)] = got
            crs.append(got[0])
            all_ports.append(got[1])
            special.append(got[2])
        live = [0 if a.client_terminal_status() else 1 for a in allocs]
        live_strict = [0 if a.terminal_status() else 1 for a in allocs]
        job_hash = [stable_hash(a.namespace, a.job_id) for a in allocs]
        jobtg_hash = [stable_hash(a.namespace, a.job_id, a.task_group)
                      for a in allocs]
        self.version += 1
        n_new = sum(1 for i in ids if i not in self._row_of)
        while self.n_rows + n_new - len(self._free) > self._cap:
            self._grow()
        rows = np.empty(len(allocs), dtype=np.int64)
        existed = np.zeros(len(allocs), dtype=bool)
        for k, a in enumerate(allocs):
            row = self._row_of.get(a.id)
            if row is None:
                if self._free:
                    row = self._free.pop()
                else:
                    row = self.n_rows
                    self.n_rows += 1
                self._row_of[a.id] = row
            else:
                existed[k] = True
            rows[k] = row
        if self._fold_inc is not None:
            # retract reused rows' old contributions (fresh/freed rows
            # contribute nothing -- and fresh rows past the old n_rows
            # hold resize garbage, so they MUST be skipped here)
            self._fold_inc_rows(rows[existed], -1)
        slot_of = self._slot_of_node
        self.node_slot[rows] = [slot_of.get(a.node_id, -1)
                                for a in allocs]
        self.cpu[rows] = [cr.cpu_shares for cr in crs]
        self.mem[rows] = [cr.memory_mb for cr in crs]
        self.disk[rows] = [cr.disk_mb for cr in crs]
        self.live[rows] = live
        self.live_strict[rows] = live_strict
        self.special[rows] = special
        self.job_hash[rows] = job_hash
        self.jobtg_hash[rows] = jobtg_hash
        if self._fold_inc is not None:
            self._fold_inc_rows(rows, +1)
        # ports: reused rows (freed or replaced) may hold stale port
        # values -- the scalar path resets every upserted row, so the
        # batch must too (vectorized), BEFORE which the accounting
        # baseline is captured
        had_ports_arr = self.ports[rows, 0] >= 0
        self.ports[rows, :] = -1
        if not any(all_ports) and not self._overflow_rows:
            # no new ports, nothing overflowed: rows that had ports
            # simply lose them
            self.rows_with_ports -= int(had_ports_arr.sum())
        else:
            for k, ports in enumerate(all_ports):
                row = int(rows[k])
                had_overflow = row in self._overflow_rows
                for pi, value in enumerate(ports[:MAX_PORTS]):
                    self.ports[row, pi] = value
                if len(ports) > MAX_PORTS:
                    self._overflow_rows.add(row)
                elif had_overflow:
                    self._overflow_rows.discard(row)
                has_ports = bool(ports)
                had = bool(had_ports_arr[k])
                if has_ports and not had:
                    self.rows_with_ports += 1
                elif had and not has_ports:
                    self.rows_with_ports -= 1

    @property
    def has_port_overflow(self) -> bool:
        return bool(self._overflow_rows)

    def remove(self, alloc_id: str) -> None:
        row = self._row_of.pop(alloc_id, None)
        if row is None:
            return
        self.version += 1
        if self._fold_inc is not None:
            self._fold_inc_row(row, -1)
        if self.ports[row, 0] >= 0:
            self.rows_with_ports -= 1
        self._overflow_rows.discard(row)
        self.live[row] = 0
        self.live_strict[row] = 0
        self.special[row] = 0
        self.node_slot[row] = -1
        self.ports[row, :] = -1
        self._free.append(row)

    # ------------------------------------------------------------------
    def pack(self, n_pad: int, node_slots_for_pad: np.ndarray,
             with_ports: bool, port_words_seed: Optional[np.ndarray] = None):
        """Fold the table into node-axis tensors aligned to the caller's
        node ordering. node_slots_for_pad[i] = table slot of the node at
        position i (or -1). Returns dict of arrays (position-indexed)."""
        n = self.n_rows
        # remap table node slots -> caller positions (vectorized; the
        # Python per-position loop ran under the store lock per lane pack)
        remap = np.full(self.n_nodes + 1, -1, dtype=np.int32)
        valid_pad = node_slots_for_pad >= 0
        remap[node_slots_for_pad[valid_pad]] = \
            np.nonzero(valid_pad)[0].astype(np.int32)
        row_slots = self.node_slot[:n]
        mapped = np.where(row_slots >= 0, remap[np.maximum(row_slots, 0)], -1)

        dyn_lo_pos = np.full(n_pad, 20000, dtype=np.int32)
        dyn_hi_pos = np.full(n_pad, 32000, dtype=np.int32)
        valid = node_slots_for_pad >= 0
        dyn_lo_pos[valid] = self.dyn_lo[node_slots_for_pad[valid]]
        dyn_hi_pos[valid] = self.dyn_hi[node_slots_for_pad[valid]]

        # Port state only matters when the asking TG has networks; skip the
        # (potentially 80MB) bitmap fold entirely otherwise.
        use_ports = with_ports and (self.rows_with_ports > 0
                                    or port_words_seed is not None)
        inc = None if use_ports else self._fold_inc_get()
        if inc is not None:
            # incremental path: gather the resident per-slot fold into the
            # caller's node ordering -- O(nodes) per pack instead of the
            # O(rows) native fold per table version (what sustained churn
            # defeats). Portless lanes see exactly what native.pack_usage
            # returns with ports=None: zero dyn_used, no bitmap.
            used_cpu = np.zeros(n_pad, dtype=np.float64)
            used_mem = np.zeros(n_pad, dtype=np.float64)
            used_disk = np.zeros(n_pad, dtype=np.float64)
            sel = node_slots_for_pad[valid]
            used_cpu[valid] = inc["uc"][sel]
            used_mem[valid] = inc["um"][sel]
            used_disk[valid] = inc["ud"][sel]
            return {"used_cpu": used_cpu, "used_mem": used_mem,
                    "used_disk": used_disk,
                    "dyn_used": np.zeros(n_pad, dtype=np.int32),
                    "port_words": None, "row_slots": mapped}
        used_cpu, used_mem, used_disk, dyn_used, port_words = \
            native.pack_usage(
                mapped.astype(np.int32), self.cpu[:n], self.mem[:n],
                self.disk[:n], self.live[:n],
                self.ports[:n] if use_ports else None,
                dyn_lo_pos, dyn_hi_pos, n_pad,
                port_words_seed=port_words_seed if with_ports else None)
        return {"used_cpu": used_cpu, "used_mem": used_mem,
                "used_disk": used_disk, "dyn_used": dyn_used,
                "port_words": port_words, "row_slots": mapped}

    def _fold_verify_all(self):
        """Per-SLOT (used_cpu, used_mem, used_disk, special_any) under the
        applier's live_strict filter, memoized by table version. One
        vectorized pass over all rows serves every fold_verify call until
        the next mutation -- the group-commit applier verifies a whole
        batch of plans between two commits, so the fold amortizes across
        the batch (and across the barrier's 32 lanes at headline shape).
        With NOMAD_TPU_PACK_DELTA on (the default) the fold is served
        straight from the incrementally-maintained columns -- no refold
        on version change at all; the version-keyed memo below is the
        kill-switch (wholesale invalidation) path."""
        inc = self._fold_inc_get()
        if inc is not None:
            n = self.n_nodes
            return (inc["vc"][:n], inc["vm"][:n], inc["vd"][:n],
                    inc["vspec"][:n] > 0)
        cache = self._verify_fold_cache
        if cache is not None and cache[0] == self.version:
            return cache[1]
        n = self.n_rows
        nslots = self.n_nodes
        used_c = np.zeros(nslots)
        used_m = np.zeros(nslots)
        used_d = np.zeros(nslots)
        spec = np.zeros(nslots, dtype=bool)
        if n and nslots:
            slots = self.node_slot[:n]
            live = (self.live_strict[:n] > 0) & (slots >= 0)
            m = slots[live]
            np.add.at(used_c, m, self.cpu[:n][live])
            np.add.at(used_m, m, self.mem[:n][live])
            np.add.at(used_d, m, self.disk[:n][live])
            spec[slots[live & (self.special[:n] > 0)]] = True
        folded = (used_c, used_m, used_d, spec)
        self._verify_fold_cache = (self.version, folded)
        return folded

    def fold_verify(self, node_ids):
        """Per-node (used_cpu, used_mem, used_disk, special_any, found)
        under the APPLIER's liveness filter (live_strict: excludes
        server-terminal too, matching AllocsByNodeTerminal(false) in
        plan_apply.go) for the plan verifier's native pre-pass. Caller
        must hold the owning store's lock (a half-committed plan would
        tear the fold). ``found[k]`` False = node unknown to the table
        (no allocs ever) -- usage is zero there. Returns fresh arrays
        (callers mutate them in place while adjusting plan deltas)."""
        npos = len(node_ids)
        slots = np.fromiter(
            (self._slot_of_node.get(i, -1) for i in node_ids),
            dtype=np.int32, count=npos)
        found = slots >= 0
        base_c, base_m, base_d, base_s = self._fold_verify_all()
        if not base_c.shape[0]:
            return (np.zeros(npos), np.zeros(npos), np.zeros(npos),
                    np.zeros(npos, dtype=bool), found)
        idx = np.where(found, slots, 0)
        used_c = np.where(found, base_c[idx], 0.0)
        used_m = np.where(found, base_m[idx], 0.0)
        used_d = np.where(found, base_d[idx], 0.0)
        spec_any = found & base_s[idx]
        return used_c, used_m, used_d, spec_any, found

    # ------------------------------------------------------------------
    def compact(self) -> dict:
        """Rebuild row storage densely: surviving allocs are repacked
        into rows [0, k), freed rows vanish, and capacity shrinks to the
        smallest power-of-two bucket holding the survivors -- the memory
        actually returns (the ports matrix alone is cap x MAX_PORTS
        int32). Called by the core-gc loop via
        StateStore.compact_alloc_table once the free-row count crosses
        the watermark; caller holds the owning store's lock."""
        items = sorted(self._row_of.items(), key=lambda kv: kv[1])
        k = len(items)
        src = np.fromiter((r for _, r in items), dtype=np.int64, count=k)
        old_rows, old_cap = self.n_rows, self._cap
        new_cap = 1024
        while new_cap < k:
            new_cap *= 2
        for name, fill in (("node_slot", -1), ("cpu", 0), ("mem", 0),
                           ("disk", 0), ("live", 0), ("live_strict", 0),
                           ("special", 0), ("job_hash", 0),
                           ("jobtg_hash", 0)):
            old = getattr(self, name)
            arr = np.full(new_cap, fill, dtype=old.dtype)
            arr[:k] = old[src]
            setattr(self, name, arr)
        ports = np.full((new_cap, MAX_PORTS), -1, dtype=np.int32)
        ports[:k] = self.ports[src]
        self.ports = ports
        row_map = {int(old): i for i, old in enumerate(src)}
        self._overflow_rows = {row_map[r] for r in self._overflow_rows
                               if r in row_map}
        self._row_of = {aid: i for i, (aid, _) in enumerate(items)}
        self.rows_with_ports = int((self.ports[:k, 0] >= 0).sum()) if k \
            else 0
        self._free = []
        self.n_rows = k
        self._cap = new_cap
        self.version += 1
        self._verify_fold_cache = None
        self._fold_inc = None       # lazily rebuilt from the dense rows
        return {"rows_before": old_rows, "rows_after": k,
                "cap_before": old_cap, "cap_after": new_cap}

    @property
    def free_rows(self) -> int:
        return len(self._free)

    def count_placed(self, n_pad: int, mapped_slots: np.ndarray,
                     namespace: str, job_id: str, tg_name: str):
        n = self.n_rows
        return native.count_placed(
            mapped_slots.astype(np.int32), self.job_hash[:n],
            self.jobtg_hash[:n], self.live[:n],
            stable_hash(namespace, job_id),
            stable_hash(namespace, job_id, tg_name), n_pad)
