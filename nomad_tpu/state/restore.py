"""Wholesale state restore: rebuild every StateStore table from a
snapshot blob.

Extracted from raft/fsm.py (ISSUE 11 ``no-direct-table-write``): this
is the ONE sanctioned writer of store internals outside the store's
own methods -- a raft snapshot install replaces the world atomically
under the store lock, and keeping it inside ``nomad_tpu/state/`` lets
the lint rule forbid direct table writes everywhere else without a
pile of waivers.  ``raft/fsm.py`` re-exports it, so the FSM surface
(and every existing import site) is unchanged.
"""
from __future__ import annotations

from typing import TYPE_CHECKING

from ..structs import (
    ACLPolicy, ACLRole, ACLToken, Allocation, CSIVolume, Deployment,
    Evaluation, Job, Namespace, Node, NodePool, RootKey,
    ScalingEvent, ScalingPolicy, SchedulerConfiguration,
    ServiceRegistration, VariableEncrypted,
)
from ..structs import codec

if TYPE_CHECKING:  # pragma: no cover
    from .store import StateStore


def restore_state(store: "StateStore", blob: dict) -> None:
    nodes = [codec.decode(Node, n) for n in blob.get("nodes", [])]
    jobs = [codec.decode(Job, j) for j in blob.get("jobs", [])]
    evals = [codec.decode(Evaluation, e) for e in blob.get("evals", [])]
    allocs = [codec.decode(Allocation, a) for a in blob.get("allocs", [])]
    deployments = [codec.decode(Deployment, d)
                   for d in blob.get("deployments", [])]
    pools = [codec.decode(NodePool, p) for p in blob.get("node_pools", [])]
    sched_cfg = codec.decode(SchedulerConfiguration,
                             blob.get("scheduler_config") or {})
    acl_policies = [codec.decode(ACLPolicy, p)
                    for p in blob.get("acl_policies", [])]
    acl_tokens = [codec.decode(ACLToken, t)
                  for t in blob.get("acl_tokens", [])]
    acl_roles = [codec.decode(ACLRole, r)
                 for r in blob.get("acl_roles", [])]
    root_keys = [codec.decode(RootKey, k)
                 for k in blob.get("root_keys", [])]
    variables = [codec.decode(VariableEncrypted, v)
                 for v in blob.get("variables", [])]
    # decode EVERYTHING before touching the store, so a malformed blob
    # raises here and leaves state untouched (restore must be atomic)
    job_versions = {}
    for k, v in blob.get("job_versions", {}).items():
        ns, jid, ver = k.split("\x1f")
        job_versions[(ns, jid, int(ver))] = codec.decode(Job, v)
    scaling_policies = {
        pol.id: pol for pol in
        (codec.decode(ScalingPolicy, raw)
         for raw in blob.get("scaling_policies", []))}
    scaling_events = {}
    for k, evs in blob.get("scaling_events", {}).items():
        ns, jid = k.split("\x1f")
        scaling_events[(ns, jid)] = [
            codec.decode(ScalingEvent, e) for e in evs]
    restored_ns = [codec.decode(Namespace, n)
                   for n in blob.get("namespaces", [])]
    csi_volumes = {
        (v.namespace, v.id): v for v in
        (codec.decode(CSIVolume, raw)
         for raw in blob.get("csi_volumes", []))}
    services = {
        svc.id: svc for svc in
        (codec.decode(ServiceRegistration, raw)
         for raw in blob.get("services", []))}
    with store._lock:
        store._root_keys = {k.key_id: k for k in root_keys}
        store._variables = {(v.meta.namespace, v.meta.path): v
                            for v in variables}
        store._acl_policies = {p.name: p for p in acl_policies}
        store._acl_roles = {r.name: r for r in acl_roles}
        store._acl_tokens = {t.accessor_id: t for t in acl_tokens}
        store._acl_tokens_by_secret = {t.secret_id: t.accessor_id
                                       for t in acl_tokens}
        store._acl_bootstrapped = blob.get("acl_bootstrapped", False)
        store._nodes = {n.id: n for n in nodes}
        store._jobs = {(j.namespace, j.id): j for j in jobs}
        store._job_versions = job_versions
        store._evals = {e.id: e for e in evals}
        store._allocs = {a.id: a for a in allocs}
        store._deployments = {d.id: d for d in deployments}
        store._node_pools = {p.name: p for p in pools}
        if sched_cfg is not None:
            store._scheduler_config = sched_cfg
        # rebuild secondary indexes (and drop the snapshot cache + its
        # incremental-copy base: both refer to the replaced dicts)
        store._allocs_by_node = {}
        store._allocs_by_job = {}
        store._snap_cache = None
        store._snap_prev = None
        store._dirty_alloc_nodes.clear()
        store._dirty_alloc_jobs.clear()
        for a in allocs:
            store._allocs_by_node.setdefault(a.node_id, {})[a.id] = None
            store._allocs_by_job.setdefault(
                (a.namespace, a.job_id), {})[a.id] = None
        # re-link alloc.job to the stored job (codec duplicates the object)
        for a in allocs:
            stored = store._jobs.get((a.namespace, a.job_id))
            if stored is not None and a.job is not None and \
                    a.job.version == stored.version:
                a.job = stored
        store._scaling_policies = scaling_policies
        store._scaling_events = scaling_events
        if restored_ns:
            store._namespaces = {n.name: n for n in restored_ns}
        else:
            store._namespaces = {"default": Namespace(name="default")}
        store._namespaces.setdefault("default", Namespace(name="default"))
        store._csi_volumes = csi_volumes
        store._recompute_csi_plugins_locked()
        store._services = services
        store._index = blob.get("index", 1)
        ti = blob.get("table_index", {})
        for t in store._table_index:
            store._table_index[t] = ti.get(t, store._index)
        # rebuild the tensor-resident alloc table
        from ..state.alloc_table import AllocTable
        table = AllocTable()
        for n in nodes:
            table.register_node(n)
        # skip only CLIENT-terminal allocs (their rows would carry
        # live=0 AND live_strict=0 -- dead weight). Server-terminal
        # but client-running allocs must keep a row: they still
        # consume capacity in the scheduler's live filter until the
        # client acks, and dropping them made solver usage tensors
        # diverge across a snapshot restore
        # (tests/test_plan_normalization.py pins this).
        table.upsert_many(
            [a for a in allocs if not a.client_terminal_status()])
        store.alloc_table = table
        store._watch_cond.notify_all()
