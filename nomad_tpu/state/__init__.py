"""MVCC state store (reference: /root/reference/nomad/state/)."""
from .store import StateStore, StateSnapshot, TABLES  # noqa: F401
