"""Bisect the wave-kernel step cost on chip: times lax.scan programs at
the headline shape (E=32 lanes vmapped, P=2048 steps, B=32 window,
C=P+B rows) with progressively larger step bodies, all on random data.
Identifies which part of the step the 38us/step goes to. Experiment
only -- no production semantics."""
import functools
import statistics
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

E, P, B = 32, 2048, 32
C = P + B
UNROLL = 8

key = jax.random.PRNGKey(0)
compact = jax.random.uniform(key, (E, C, 12), dtype=jnp.float32) + 1.0
pen = jnp.zeros((E, P), dtype=jnp.int32) - 1


def timeit(name, fn, *args):
    f = jax.jit(fn)
    out = f(*args)
    jax.block_until_ready(out)
    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        out = f(*args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    med = statistics.median(times)
    print(f"{name:<28} {med*1000:8.2f}ms  {med/P*1e6:6.2f}us/step",
          flush=True)
    return med


arangeB = jnp.arange(B, dtype=jnp.int32)
arangeC = jnp.arange(C, dtype=jnp.int32)


def scan_over(step, n_carry_extra=0):
    def one_lane(compact_l, pen_l):
        slot0 = compact_l[:B]
        carry0 = (jnp.zeros(B, jnp.int32), slot0, jnp.int32(B))
        _, ys = jax.lax.scan(
            functools.partial(step, compact_l=compact_l), carry0,
            (jnp.arange(P, dtype=jnp.int32), pen_l), unroll=UNROLL)
        return ys
    return jax.vmap(one_lane)


# --- variant 1: trivial body (scan floor) ---
def step_floor(carry, xs, compact_l):
    j, slot, cursor = carry
    i, pen_i = xs
    return (j + 1, slot, cursor + 1), (i, jnp.float32(0.0), i)


# --- variant 2: score math only (elementwise over B + argmax) ---
def step_score(carry, xs, compact_l):
    j, slot, cursor = carry
    i, pen_i = xs
    cs = slot[:, 0]
    fit = j.astype(jnp.float32) < cs
    jp1 = (j + 1).astype(jnp.float32)
    new_cpu = slot[:, 1] + jp1 * 0.5
    new_mem = slot[:, 2] + jp1 * 0.5
    free_cpu = 1.0 - new_cpu / jnp.maximum(slot[:, 3], 1e-9)
    free_mem = 1.0 - new_mem / jnp.maximum(slot[:, 4], 1e-9)
    binpack = 18.0 - jnp.exp2(-10.0 * free_cpu) - jnp.exp2(-10.0 * free_mem)
    coll = slot[:, 5] + j.astype(jnp.float32)
    anti = jnp.where(coll > 0, -(coll + 1.0) / 2000.0, 0.0)
    is_pen = (pen_i >= 0) & (slot[:, 7] == pen_i.astype(jnp.float32))
    final = (binpack + anti + jnp.where(is_pen, -1.0, 0.0) + slot[:, 6])
    eff = jnp.where(fit, final, -jnp.inf)
    w = jnp.argmax(eff)
    oh_w = arangeB == w
    j2 = j + oh_w.astype(jnp.int32)
    return (j2, slot, cursor), (w, jnp.max(eff), i)


# --- variant 3: score + selection-window cumsums ---
def step_select(carry, xs, compact_l):
    j, slot, cursor = carry
    i, pen_i = xs
    cs = slot[:, 0]
    fit = j.astype(jnp.float32) < cs
    jp1 = (j + 1).astype(jnp.float32)
    free_cpu = 1.0 - (slot[:, 1] + jp1 * 0.5) / jnp.maximum(slot[:, 3], 1e-9)
    free_mem = 1.0 - (slot[:, 2] + jp1 * 0.5) / jnp.maximum(slot[:, 4], 1e-9)
    final = 18.0 - jnp.exp2(-10.0 * free_cpu) - jnp.exp2(-10.0 * free_mem)
    low = fit & (final <= 0.0)
    skip_rank = jnp.cumsum(low.astype(jnp.int32))
    skipped = low & (skip_rank <= 3)
    counted = fit & ~skipped
    cpos = jnp.cumsum(counted.astype(jnp.int32))
    window = counted & (cpos <= 8)
    srank = jnp.cumsum(skipped.astype(jnp.int32))
    fallback = skipped & (srank <= 2)
    yielded = window | fallback
    order = jnp.where(window, cpos, 8 + srank)
    eff = jnp.where(yielded, final, -jnp.inf)
    best = jnp.max(eff)
    is_best = yielded & (eff == best)
    border = jnp.min(jnp.where(is_best, order, 2 ** 30))
    w = jnp.argmax(is_best & (order == border))
    oh_w = arangeB == w
    j2 = j + oh_w.astype(jnp.int32)
    return (j2, slot, cursor), (w, best, jnp.sum(yielded.astype(jnp.int32)))


# --- variant 4: score + select + refill/shift (the full structure) ---
def step_full(carry, xs, compact_l):
    (j2, slot, cursor), (w, best, ny) = step_select(carry, xs, compact_l)
    i, pen_i = xs
    oh_w = arangeB == w
    cs = slot[:, 0]
    jw = jnp.sum(jnp.where(oh_w, j2, 0), dtype=jnp.int32)
    csw = jnp.sum(jnp.where(oh_w, cs, 0.0))
    sat = jw.astype(jnp.float32) >= csw
    oh_c = arangeC == jnp.clip(cursor, 0, C - 1)
    entry_row = jnp.sum(jnp.where(oh_c[:, None], compact_l, 0.0), axis=0)
    take_next = arangeB >= w
    is_last = arangeB == B - 1
    j_sh = jnp.where(is_last, 0,
                     jnp.where(take_next, jnp.roll(j2, -1), j2))
    slot_sh = jnp.where(
        is_last[:, None], entry_row[None, :],
        jnp.where(take_next[:, None], jnp.roll(slot, -1, axis=0), slot))
    j3 = jnp.where(sat, j_sh, j2)
    slot2 = jnp.where(sat, slot_sh, slot)
    cursor2 = cursor + sat.astype(jnp.int32)
    return (j3, slot2, cursor2), (w, best, ny)


print(f"backend={jax.default_backend()} E={E} P={P} B={B} unroll={UNROLL}",
      flush=True)
timeit("floor (trivial body)", scan_over(step_floor), compact, pen)
timeit("score+argmax", scan_over(step_score), compact, pen)
timeit("score+window-select", scan_over(step_select), compact, pen)
timeit("full (incl refill/shift)", scan_over(step_full), compact, pen)


# --- finer bisect: what inside score+argmax costs ---
def step_ew_only(carry, xs, compact_l):
    """Elementwise score math, NO reductions (winner = rotating slot)."""
    j, slot, cursor = carry
    i, pen_i = xs
    jp1 = (j + 1).astype(jnp.float32)
    free_cpu = 1.0 - (slot[:, 1] + jp1 * 0.5) / jnp.maximum(slot[:, 3], 1e-9)
    free_mem = 1.0 - (slot[:, 2] + jp1 * 0.5) / jnp.maximum(slot[:, 4], 1e-9)
    final = 18.0 - jnp.exp2(-10.0 * free_cpu) - jnp.exp2(-10.0 * free_mem)
    oh_w = arangeB == (i % B)
    j2 = j + oh_w.astype(jnp.int32) + (final > 17.0).astype(jnp.int32)
    return (j2, slot, cursor), (i % B, final[0], i)


def step_argmax_only(carry, xs, compact_l):
    """Minimal elementwise + argmax reduction."""
    j, slot, cursor = carry
    i, pen_i = xs
    eff = slot[:, 0] - j.astype(jnp.float32)
    w = jnp.argmax(eff)
    oh_w = arangeB == w
    j2 = j + oh_w.astype(jnp.int32)
    return (j2, slot, cursor), (w, jnp.max(eff), i)


def step_argmax_noout(carry, xs, compact_l):
    """argmax chain with SCALAR-free outputs (no per-step ys writes)."""
    j, slot, cursor = carry
    i, pen_i = xs
    eff = slot[:, 0] - j.astype(jnp.float32)
    w = jnp.argmax(eff)
    oh_w = arangeB == w
    j2 = j + oh_w.astype(jnp.int32)
    return (j2, slot, cursor), None


def scan_noout(step):
    def one_lane(compact_l, pen_l):
        slot0 = compact_l[:B]
        carry0 = (jnp.zeros(B, jnp.int32), slot0, jnp.int32(B))
        out, _ = jax.lax.scan(
            functools.partial(step, compact_l=compact_l), carry0,
            (jnp.arange(P, dtype=jnp.int32), pen_l), unroll=UNROLL)
        return out[0]
    return jax.vmap(one_lane)


timeit("ew-score only (no reduce)", scan_over(step_ew_only), compact, pen)
timeit("argmax only", scan_over(step_argmax_only), compact, pen)
timeit("argmax, no ys outputs", scan_noout(step_argmax_noout), compact, pen)

# --- E scaling at fixed P (latency-bound => ~flat) ---
for e2 in (64, 128, 256):
    k2 = jax.random.PRNGKey(e2)
    c2 = jax.random.uniform(k2, (e2, C, 12), dtype=jnp.float32) + 1.0
    p2 = jnp.zeros((e2, P), dtype=jnp.int32) - 1
    med = timeit(f"full @ E={e2}", scan_over(step_full), c2, p2)
    print(f"   -> {e2*P/med/1e6:.2f}M placements/s", flush=True)


# --- in-dispatch repeat: amortize the tunnel RTT out of the measurement
# (one jit call runs the kernel R times, chained through a data dep) ---
def chained(step, R):
    def run(compact_b, pen_b):
        def once(x, _):
            c2 = compact_b + x * 1e-12
            ys = scan_over(step)(c2, pen_b)
            # fold outputs to a scalar that feeds the next iteration
            s = ys[1].sum()
            return s, s
        out, _ = jax.lax.scan(once, jnp.float32(0), None, length=R)
        return out
    return run


for R in (1, 4, 16):
    f = jax.jit(chained(step_full, R))
    _ = np.asarray(f(compact, pen))
    ts = []
    for _ in range(4):
        t0 = time.perf_counter()
        _ = np.asarray(f(compact, pen))
        ts.append(time.perf_counter() - t0)
    print(f"full kernel xR={R:<3} sync median {statistics.median(ts)*1000:8.2f}ms",
          flush=True)
