#!/usr/bin/env python3
"""Bench-trend gate: compare a fresh BENCH artifact's headline fields
against the previous round's json with configurable tolerances.

Usage:
    scripts/check_bench_regress.py NEW.json [--against OLD.json]
        [--tol FIELD=FRAC ...] [--require FIELD ...]

Without --against, the previous artifact is auto-discovered from the
repo root: the artifact OF THE SAME VARIANT (headline BENCH_rNN.json
vs suffixed BENCH_rNN_tier3.json / BENCH_rNN_headline.json -- suffixes
never cross-pair) with the highest round number strictly below the new
artifact's (stamped ``round_id``, falling back to the filename).
Every artifact carries ``round_id``/``git_sha``/``run_id`` via
benchkit.artifact_stamp, so the pairing is by stamp, not mtime.

A field regresses when it moves in its BAD direction by more than the
tolerance fraction: throughput-style fields (higher-better) must not
drop below ``prev * (1 - tol)``; latency/RSS-style fields
(lower-better) must not rise above ``prev * (1 + tol)``.  Fields
missing on either side are skipped with a warning (a new round may add
metrics; an old one may predate them) unless listed in --require.
Hard invariants regardless of tolerances: ``parity_mismatch`` must be
0 and ``degraded`` must not be newly truthy.

Exit 0: no regression.  Exit 1: regressions listed on stdout.
Exit 2: usage/IO errors.  The comparison logic is pure
(`compare_artifacts`) and tier-1-gated on fixture artifacts by
tests/test_bench_regress.py.
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# field -> (direction, default tolerance fraction)
HEADLINE_FIELDS = {
    "value": ("higher", 0.10),
    "fused_placements_per_sec": ("higher", 0.10),
    "fused_compute_placements_per_sec": ("higher", 0.10),
    "fused_compute_marginal_placements_per_sec": ("higher", 0.10),
    "batched_full_placements_per_sec": ("higher", 0.10),
    "streaming_pipelined_placements_per_sec": ("higher", 0.15),
    "scale_placements_per_sec": ("higher", 0.15),
    "pack_warm_cut": ("higher", 0.25),
    "dispatch_bytes_cut": ("higher", 0.25),
    "control_plane_tax": ("lower", 0.15),
    "churn_p50_ms": ("lower", 0.25),
    "churn_p99_ms": ("lower", 0.25),
    "churn_rss_growth_mb": ("lower", 0.50),
    # N-worker control plane scaling (ISSUE 16): e2e throughput per
    # pool size through the supervised plain worker pool; the parity
    # field is 0 on a healthy round (any positive count regresses)
    "worker_scaling_pps_n1": ("higher", 0.25),
    "worker_scaling_pps_n4": ("higher", 0.25),
    "worker_scaling_pps_n8": ("higher", 0.25),
    "worker_scaling_parity_mismatch": ("lower", 0.0),
    "scale_rss_mb": ("lower", 0.15),
    "quality_fragmentation": ("lower", 0.25),
    "quality_drift": ("lower", 0.50),
    "lpq_placements_per_sec": ("higher", 0.15),
    "lpq_evals_per_solve": ("higher", 0.25),
    "lpq_repair_rate": ("lower", 0.50),
    # dispatch discipline (ISSUE 10): all three are 0 on a healthy
    # round; the zero-previous epsilon rule means ANY positive count is
    # a regression (a steady-state retrace or hot-path sync crept in)
    "jit_retrace_count": ("lower", 0.0),
    "jit_host_sync_count": ("lower", 0.0),
    "jit_x64_leaks": ("lower", 0.0),
    # snapshot isolation (ISSUE 11): all five are 0 on a healthy
    # round; any positive count vs a zero round is a regression (a
    # torn read / aliasing write / silent journal gap / write skew /
    # stale memo crept in)
    "state_torn_reads": ("lower", 0.0),
    "state_aliasing_writes": ("lower", 0.0),
    "state_journal_gaps": ("lower", 0.0),
    "state_write_skews": ("lower", 0.0),
    "state_stale_memos": ("lower", 0.0),
    # sharding discipline (ISSUE 15): all three are 0 on a healthy
    # round; any positive count vs a zero round is a regression (a
    # replicated-when-declared-sharded table, a silent reshard into a
    # mesh callable, or an unbudgeted steady-state collective crept in)
    "shard_spec_drift": ("lower", 0.0),
    "shard_implicit_xfer": ("lower", 0.0),
    "shard_collective_excess": ("lower", 0.0),
    # transfer observatory (ISSUE 13): the per-dispatch payload must
    # not bloat (ROADMAP-4 wants it SHRINKING toward KB), the fitted
    # link must not slow down, and the ledger's byte parity vs
    # dispatch_bytes_total is 0 on a healthy round -- any positive
    # parity vs a zero round means a transport's bytes escaped the
    # decomposition
    "xfer_shipped_bytes_per_dispatch": ("lower", 0.25),
    "xfer_rtt_ms": ("lower", 0.50),
    "xfer_bw_mbps": ("higher", 0.50),
    "xfer_ledger_parity": ("lower", 0.0),
    # per-eval fixed cost (ISSUE 17): the microbench the native
    # control-plane kernels move -- snapshot build + plan verify +
    # materialize, isolated from solver time
    "eval_fixed_ms": ("lower", 0.25),
    # multi-chip mesh solve (ISSUE 19): mesh-route throughput must not
    # fall, per-shard ship bytes and collective overhead must not
    # bloat, and mesh-vs-single-device parity is zero-tolerance (the
    # mesh route is bit-exact by construction; ANY positive count
    # means a re-associated reduction crept into a kernel)
    "mesh_pps": ("higher", 0.25),
    "mesh_shard_bytes": ("lower", 0.25),
    "mesh_collective_ms": ("lower", 0.50),
    "mesh_parity_mismatch": ("lower", 0.0),
    # delta streaming (ISSUE 20): warm steady-state churn payload per
    # dispatch must not bloat back toward full-table re-ships, wholesale
    # fallbacks must not grow (a journal gap or a diff-too-big slot
    # crept into the steady state), and the churn round's transfer
    # ledger parity is zero-tolerance like the headline's
    "churn_delta_bytes_per_dispatch": ("lower", 0.25),
    "churn_shipped_bytes_per_dispatch": ("lower", 0.25),
    "churn_delta_fallbacks": ("lower", 0.50),
    "churn_xfer_ledger_parity": ("lower", 0.0),
    "delta_fallbacks": ("lower", 0.50),
}

# Absolute noise floors for lower-better fields whose round-to-round
# variance is intrinsic, not a trend.  quality_drift is the max score
# delta over the shadow audit's SAMPLED solves, and the sample size
# (quality_audited) is thread-timing dependent -- identical code drew
# 2.6e-08 / 0.192 / 0.273 (r07, audited=3) and 0.426 / 0.584 (r08,
# audited=8), so below O(1) the row cannot distinguish an unlucky draw
# from a regression; a relative tolerance on a near-zero previous
# value turns that noise into a hard failure.  Catastrophic score-math
# breakage still trips this row (drift >> 1), and the deterministic
# quality signals stay live: the in-server violating-audit breaker
# (NOMAD_TPU_QUALITY_DRIFT_TOL) and the quality_decision_mismatch
# trend.  A current value at or below the floor never regresses,
# whatever the previous value was; movements ABOVE the floor still
# face the relative gate.
NOISE_FLOOR = {
    "quality_drift": 1.0,
}


def compare_artifacts(prev: dict, cur: dict,
                      tol_overrides: dict | None = None,
                      require: tuple = ()) -> tuple:
    """Pure comparison: returns (regressions, warnings) -- lists of
    human-readable strings; empty regressions = gate passes."""
    tol_overrides = tol_overrides or {}
    regressions, warnings = [], []

    # hard invariants first: a parity break or a newly degraded run is
    # never excused by a tolerance
    if cur.get("parity_mismatch"):
        regressions.append(
            f"parity_mismatch={cur['parity_mismatch']} (must be 0)")
    if cur.get("degraded") and not prev.get("degraded"):
        regressions.append(
            f"run newly degraded: {cur['degraded']!r} "
            f"(previous round was healthy)")

    for field, (direction, default_tol) in sorted(HEADLINE_FIELDS.items()):
        tol = tol_overrides.get(field, default_tol)
        pv, cv = prev.get(field), cur.get(field)
        if pv is None or cv is None:
            missing = [s for s, v in (("previous", pv), ("current", cv))
                       if v is None]
            msg = f"{field}: missing in {'/'.join(missing)} artifact"
            if field in require:
                regressions.append(msg + " (required)")
            else:
                warnings.append(msg)
            continue
        try:
            pv, cv = float(pv), float(cv)
        except (TypeError, ValueError):
            warnings.append(f"{field}: non-numeric ({pv!r} -> {cv!r})")
            continue
        if direction == "higher":
            floor = pv * (1.0 - tol)
            if cv < floor:
                regressions.append(
                    f"{field}: {cv:g} < {pv:g} - {tol:.0%} "
                    f"(floor {floor:g})")
        else:
            floor = NOISE_FLOOR.get(field)
            if floor is not None and cv <= floor:
                # intrinsic measurement noise, not a trend: 2.6e-08 ->
                # 0.273 on identical runs must not trip a relative gate
                continue
            # a zero/near-zero previous value gets an absolute epsilon
            # so 0 -> 0.001 noise does not fail a 25% relative gate
            ceil = pv * (1.0 + tol) if pv > 1e-9 else tol
            if cv > ceil:
                regressions.append(
                    f"{field}: {cv:g} > {pv:g} + {tol:.0%} "
                    f"(ceiling {ceil:g})")
    return regressions, warnings


def _round_num(artifact: dict, path: str) -> int:
    rid = str(artifact.get("round_id") or "")
    m = re.match(r"r?(\d+)", rid) or \
        re.search(r"BENCH_r(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else -1


_ARTIFACT = re.compile(r"BENCH_r(\d+)((?:_[A-Za-z0-9]+)*)\.json$")


def _round_suffix(path: str) -> str:
    """The artifact's variant suffix: '' for headline BENCH_rNN.json,
    '_tier3'/'_headline'/... for tiered artifacts."""
    m = _ARTIFACT.match(os.path.basename(path))
    return m.group(2) if m else ""


def discover_previous(cur_path: str, cur: dict,
                      root: str = ROOT) -> str | None:
    """Latest BENCH artifact OF THE SAME VARIANT with a round number
    strictly below the current artifact's (same-round reruns are not a
    trend).  Suffixed artifacts (BENCH_r05_tier3.json,
    BENCH_r05_headline.json) only ever pair with the same suffix:
    comparing a tier's fields against a headline artifact -- or
    resolving "previous round" THROUGH a tiered artifact -- gates
    apples against oranges."""
    cur_round = _round_num(cur, cur_path)
    cur_suffix = _round_suffix(cur_path)
    best, best_n = None, -1
    for name in os.listdir(root):
        m = _ARTIFACT.match(name)
        if not m or m.group(2) != cur_suffix:
            continue
        n = int(m.group(1))
        path = os.path.join(root, name)
        if os.path.abspath(path) == os.path.abspath(cur_path):
            continue
        if (cur_round < 0 or n < cur_round) and n > best_n:
            best, best_n = path, n
    return best


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("artifact", help="fresh BENCH json")
    ap.add_argument("--against", default=None,
                    help="previous round's BENCH json "
                    "(default: auto-discover BENCH_rNN.json)")
    ap.add_argument("--tol", action="append", default=[],
                    metavar="FIELD=FRAC",
                    help="override a field's tolerance fraction")
    ap.add_argument("--require", action="append", default=[],
                    metavar="FIELD",
                    help="fail (not warn) when FIELD is missing")
    args = ap.parse_args(argv)

    try:
        with open(args.artifact, encoding="utf-8") as f:
            cur = json.load(f)
    except (OSError, ValueError) as e:
        print(f"ERROR: cannot read {args.artifact}: {e}")
        return 2
    prev_path = args.against or discover_previous(args.artifact, cur)
    if prev_path is None:
        print("no previous BENCH_rNN.json found; nothing to gate")
        return 0
    try:
        with open(prev_path, encoding="utf-8") as f:
            prev = json.load(f)
    except (OSError, ValueError) as e:
        print(f"ERROR: cannot read {prev_path}: {e}")
        return 2

    overrides = {}
    for spec in args.tol:
        field, _, frac = spec.partition("=")
        try:
            overrides[field] = float(frac)
        except ValueError:
            print(f"ERROR: bad --tol {spec!r} (want FIELD=FRAC)")
            return 2

    regressions, warnings = compare_artifacts(
        prev, cur, overrides, tuple(args.require))
    for w in warnings:
        print(f"warning: {w}")
    tag = (f"{prev.get('round_id', '?')}@{prev.get('git_sha', '?')} -> "
           f"{cur.get('round_id', '?')}@{cur.get('git_sha', '?')}")
    if regressions:
        print(f"{len(regressions)} regression(s) vs {prev_path} ({tag}):")
        for r in regressions:
            print(f"  {r}")
        return 1
    print(f"no regressions vs {prev_path} ({tag})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
