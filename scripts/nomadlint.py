#!/usr/bin/env python3
"""nomadlint: the repo-invariant lint driver (AST-based).

One gate for the invariants that keep the concurrent control plane
honest -- the static complement of the runtime lock-order sanitizer
(nomad_tpu/lockcheck.py).  Scans nomad_tpu/ + bench.py (rules that
read docs/tests pull those in too) and fails listing violations.

AST rules:

  fire-registered    every ``faults.fire("<point>")`` call site names
                     a literal member of nomad_tpu/faultinject.py
                     ``POINTS`` -- an unregistered point is a chaos
                     scenario nobody can arm
  killswitch-tested  every knob row in docs/OPERATIONS.md whose
                     description says "kill switch" is referenced by
                     at least one test under tests/ (a kill switch
                     without a parity test is a rollback nobody
                     verified)
  telemetry-literal  telemetry series names are string literals or
                     normalizable f-strings/ternaries (a computed name
                     can never be checked against the metrics doc)
  telemetry-kind     no series is emitted as two kinds (e.g. both
                     counter and timer) -- exactly the class of bug
                     that rendered ``batch_lanes`` as ms for 2 rounds
  sleep-under-lock   no ``time.sleep``, blocking/indefinite dequeue or
                     wait, or device dispatch statically inside a
                     ``with <lock>:`` block -- one sleeping holder
                     starves every peer for the duration
  bare-acquire       a bare ``<x>.acquire()`` statement requires a
                     try/finally releasing the same receiver (either
                     immediately following, or an enclosing try) -- an
                     exception between acquire and release wedges the
                     lock forever

Dispatch-hygiene rules (ISSUE 10, the static complement of the
runtime dispatch-discipline sanitizer nomad_tpu/jitcheck.py):

  no-callsite-jit    every ``jax.jit`` is constructed at module level
                     or inside an ``lru_cache``'d shape-bucket
                     factory -- a jit built per call defeats the
                     compile cache and re-traces every generation
  no-host-sync-hot   no ``jax.device_get`` / ``.item()`` /
                     ``block_until_ready`` inside a solver hot
                     function (one that calls a dispatch/transfer
                     primitive) or statically inside a ``with
                     <lock>:`` block; the designed one-bulk-fetch
                     sites mark themselves with
                     ``with jitcheck.sanctioned_fetch():``
  dtype-threaded     device-kernel modules (nomad_tpu/solver/,
                     nomad_tpu/parallel/) take their dtype through
                     the static ``dtype_name`` arg -- no bare
                     ``jnp.float64`` / float64 dtype literals in jnp
                     calls (on TPU f64 is emulated; a leaked float64
                     table doubles transfer and compute)
  frozen-memo        arrays stored into memo/cache containers are
                     frozen first (a freeze/setflags call in the same
                     function) -- the runtime counterpart is
                     jitcheck's writeable=False invariant
  fetch-accounted    every ``jitcheck.sanctioned_fetch(...)`` site
                     passes a non-empty string-literal ledger tag
                     (ISSUE 13): the transfer observatory attributes
                     fetched result bytes per transport, and an
                     untagged fetch is a payload the ledger cannot
                     decompose

Store-discipline rules (ISSUE 11, the static complement of the MVCC
snapshot-isolation sanitizer nomad_tpu/statecheck.py):

  no-direct-table-write  AllocTable mutators and StateStore internals
                     (``_allocs``/``_nodes``/... dict writes, alloc-
                     table column stores) are only touched from
                     ``nomad_tpu/state/`` -- everything else goes
                     through the store's locked write API
  version-keyed-memo store-derived caches (``*_CACHE``/``*memo*``
                     containers in solver/tensor/server modules) must
                     key on a table version/index/token/fingerprint
                     component -- a content-blind key serves stale
                     state forever
  no-snapshot-escape a ``state.snapshot()`` handle stored into a
                     module global or a long-lived ``self.`` attribute
                     outlives its consistency window (snapshots are
                     per-eval views, not caches)
  delta-carried      ``_bump("allocs"...)`` calls in the store carry
                     ``delta=`` (the alloc-delta journal entry) or a
                     justified waiver -- a delta-less write silently
                     degrades every incremental-memo holder to
                     wholesale rebuilds (statecheck check c is the
                     runtime twin)

Shard-hygiene rules (ISSUE 15, the static complement of the
sharding-discipline sanitizer nomad_tpu/shardcheck.py):

  spec-declared      ``PartitionSpec`` / ``NamedSharding`` are only
                     constructed inside ``nomad_tpu/parallel/`` -- the
                     spec registry (parallel/mesh.py ``SPEC_GROUPS``)
                     is the ONE home for sharding intent; an inline
                     spec elsewhere is a sharding contract no
                     sanitizer compares against
  mesh-factory       ``jax.sharding.Mesh`` is only constructed by the
                     parallel/ factories (``make_mesh`` /
                     ``pick_mesh`` / ``eval_axis_mesh``) -- an inline
                     Mesh defeats the factory's lru-cache keying and
                     silently forks the topology the registry
                     declares specs against
  no-implicit-put    ``jax.device_put`` carrying a sharding argument
                     only inside ``nomad_tpu/parallel/`` -- everything
                     else routes through ``shard_solver_inputs`` /
                     ``device_put_cached`` so the transfer ledger and
                     the per-shard byte rows see every sharded upload

Schedule-hygiene rules (ISSUE 12, the static complement of the
deterministic schedule explorer nomad_tpu/schedcheck.py):

  join-with-timeout  no indefinite ``Thread.join()`` / ``Event.wait()``
                     outside shutdown paths -- a wedged thread must
                     surface as a diagnosable stall, not an invisible
                     infinite join (and a bounded loop gives schedcheck
                     an interposition point)
  no-sleep-sync      tests/ may not synchronize threads via bare
                     ``time.sleep`` in a test body (the #1 source of
                     1-core flakes); poll loops and nested
                     simulated-work stubs are exempt
  daemon-declared    every repo ``threading.Thread(...)`` sets
                     ``daemon=`` explicitly (daemon-ness inherits from
                     the creator, so an undeclared spawn site's
                     shutdown behavior depends on its caller)

Output/maintenance flags: ``--sarif PATH`` additionally emits the kept
violations as SARIF 2.1.0 for CI/editor annotations;
``--fix-stale-waivers [--apply]`` deletes waiver comment lines whose
every named rule no longer fires there (dry-run by default).

Legacy checkers, invocable as rules under this driver (their
standalone scripts keep working; tests/test_metrics_doc.py etc. are
unchanged):

  metrics-doc        scripts/check_metrics_doc.py
  knob-doc           scripts/check_knob_doc.py
  bench-regress      scripts/check_bench_regress.py (takes the
                     artifact argv after ``--``, e.g.
                     ``nomadlint.py --rule bench-regress -- BENCH.json``)

The default run (no ``--rule``) is every AST rule plus metrics-doc and
knob-doc; bench-regress needs an artifact argument so it only runs
when selected.  Tier-1 gates the default run via
tests/test_nomadlint.py.

Waivers (per rule, justification REQUIRED after ``--``)::

    something.acquire()   # nomadlint: waive=bare-acquire -- released
                          # by the runner thread when the job retires

on the violating line or the line directly above it.  A waiver without
a ``--`` justification does not suppress anything.
"""
from __future__ import annotations

import argparse
import ast
import importlib.util
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WAIVER = re.compile(
    r"nomadlint:\s*waive=([A-Za-z0-9_,-]+)\s*--\s*\S")

# telemetry emit methods -> series kind (server/telemetry.py contract;
# _count is tracing.py's guarded incr wrapper)
_TELEMETRY_KINDS = {"incr": "counter", "sample": "gauge",
                    "sample_ms": "timer", "measure": "timer",
                    "_count": "counter"}
# receiver tails that identify a telemetry call (avoids random.sample
# and friends); _count is a self-method in tracing.py
_TELEMETRY_RECV = re.compile(r"(?:^|\.)(?:metrics|_tm|t)$")

_LOCKISH = re.compile(r"(?:lock|mutex|cv|cond|sem)\w*$", re.IGNORECASE)

_DISPATCH_CALLS = {"run_dispatch", "solve_lane_fused", "fuse_and_solve",
                   "solve_groups", "block_until_ready", "device_put"}


class Violation:
    __slots__ = ("rule", "path", "line", "msg")

    def __init__(self, rule: str, path: str, line: int, msg: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.msg = msg

    def __repr__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.msg}"


class Ctx:
    """Everything the rules read, built once per run. ``root`` is
    swappable so rule fixture tests lint a synthetic tree."""

    def __init__(self, root: str):
        self.root = root
        self.files: List[Tuple[str, str, ast.AST]] = []
        self.parse_errors: List[Violation] = []
        scan = []
        bench = os.path.join(root, "bench.py")
        if os.path.exists(bench):
            scan.append(bench)
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(root, "nomad_tpu")):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            scan.extend(os.path.join(dirpath, f)
                        for f in sorted(filenames) if f.endswith(".py"))
        for path in scan:
            rel = os.path.relpath(path, root)
            try:
                with open(path, encoding="utf-8") as f:
                    text = f.read()
                tree = ast.parse(text, filename=rel)
            except (OSError, SyntaxError) as e:
                self.parse_errors.append(Violation(
                    "parse", rel, getattr(e, "lineno", 0) or 0,
                    f"cannot parse: {e}"))
                continue
            self.files.append((rel, text, tree))

    # -- lazy context shared by repo-level rules -----------------------
    def doc_text(self) -> str:
        try:
            with open(os.path.join(self.root, "docs", "OPERATIONS.md"),
                      encoding="utf-8") as f:
                return f.read()
        except OSError:
            return ""

    def test_texts(self) -> Dict[str, str]:
        out = {}
        tdir = os.path.join(self.root, "tests")
        if not os.path.isdir(tdir):
            return out
        for name in sorted(os.listdir(tdir)):
            if not name.endswith(".py"):
                continue
            try:
                with open(os.path.join(tdir, name),
                          encoding="utf-8") as f:
                    out[f"tests/{name}"] = f.read()
            except OSError:
                continue
        return out

    def fire_points(self) -> Optional[set]:
        """POINTS tuple parsed from nomad_tpu/faultinject.py (None if
        the file or the assignment is absent)."""
        for rel, _text, tree in self.files:
            if rel != os.path.join("nomad_tpu", "faultinject.py"):
                continue
            for node in tree.body:
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == "POINTS"
                        for t in node.targets):
                    if isinstance(node.value, (ast.Tuple, ast.List)):
                        return {e.value for e in node.value.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, str)}
            return None
        return None


def _unparse(node) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 -- lint must not crash on exotica
        return "<?>"


def _normalize_name(node) -> Optional[str]:
    """Literal / normalizable telemetry name, placeholders as '*';
    None when the name cannot be statically derived."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return re.sub(r"\{[^}]*\}", "*", node.value)
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        return "".join(parts)
    if isinstance(node, ast.IfExp):
        a = _normalize_name(node.body)
        b = _normalize_name(node.orelse)
        if a is not None and b is not None:
            # both arms contribute; kind stability checks each
            return a if a == b else f"{a}|{b}"
        return None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        a = _normalize_name(node.left)
        b = _normalize_name(node.right)
        if a is not None and b is not None:
            return a + b
        return None
    return None


# ----------------------------------------------------------------------
# AST rules


def rule_fire_registered(ctx: Ctx) -> List[Violation]:
    points = ctx.fire_points()
    out: List[Violation] = []
    if points is None:
        out.append(Violation("fire-registered",
                             "nomad_tpu/faultinject.py", 0,
                             "no POINTS registry found"))
        return out
    for rel, _text, tree in ctx.files:
        if rel.endswith(os.path.join("nomad_tpu", "faultinject.py")):
            continue            # the registry/dispatcher itself
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "fire"):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                out.append(Violation(
                    "fire-registered", rel, node.lineno,
                    f"fire() point must be a string literal, got "
                    f"`{_unparse(arg)}`"))
                continue
            if arg.value not in points:
                out.append(Violation(
                    "fire-registered", rel, node.lineno,
                    f"fire point {arg.value!r} is not registered in "
                    f"faultinject.POINTS"))
    return out


def rule_killswitch_tested(ctx: Ctx) -> List[Violation]:
    doc = ctx.doc_text()
    if not doc:
        return [Violation("killswitch-tested", "docs/OPERATIONS.md", 0,
                          "docs/OPERATIONS.md missing or unreadable")]
    tests = ctx.test_texts()
    blob = "\n".join(tests.values())
    out: List[Violation] = []
    for i, line in enumerate(doc.splitlines(), 1):
        s = line.lstrip()
        if not s.startswith("|"):
            continue
        if not re.search(r"kill[ -]switch", s, re.IGNORECASE):
            continue
        for knob in re.findall(r"`(NOMAD_TPU_[A-Z0-9_]+)`", s):
            if knob not in blob:
                out.append(Violation(
                    "killswitch-tested", "docs/OPERATIONS.md", i,
                    f"kill-switch knob {knob} is not referenced by any "
                    f"test under tests/ (no parity gate)"))
    return out


def rule_telemetry(ctx: Ctx) -> List[Violation]:
    """Shared scan for telemetry-literal and telemetry-kind."""
    out: List[Violation] = []
    seen: Dict[str, Tuple[str, str, int]] = {}   # name -> (kind, at)
    for rel, _text, tree in ctx.files:
        if rel.endswith(os.path.join("nomad_tpu", "server",
                                     "telemetry.py")):
            continue            # the sink's own generic dispatch
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _TELEMETRY_KINDS
                    and node.args):
                continue
            recv = _unparse(node.func.value)
            if node.func.attr == "_count":
                if recv != "self":
                    continue
            elif not _TELEMETRY_RECV.search(recv):
                continue
            name = _normalize_name(node.args[0])
            if name is None:
                out.append(Violation(
                    "telemetry-literal", rel, node.lineno,
                    f"telemetry series name must be a literal or "
                    f"normalizable f-string, got "
                    f"`{_unparse(node.args[0])}`"))
                continue
            kind = _TELEMETRY_KINDS[node.func.attr]
            for arm in name.split("|"):
                if not arm.startswith("nomad."):
                    continue
                prev = seen.get(arm)
                if prev is None:
                    seen[arm] = (kind, rel, node.lineno)
                elif prev[0] != kind:
                    out.append(Violation(
                        "telemetry-kind", rel, node.lineno,
                        f"series {arm!r} emitted as {kind} here but as "
                        f"{prev[0]} at {prev[1]}:{prev[2]} -- one "
                        f"series, one kind"))
    return out


def _is_lockish(expr: ast.AST) -> bool:
    s = _unparse(expr)
    tail = s.split(".")[-1]
    return bool(_LOCKISH.search(tail))


class _UnderLockVisitor(ast.NodeVisitor):
    def __init__(self, rel: str, out: List[Violation]):
        self.rel = rel
        self.out = out
        self.lock_stack: List[str] = []
        self.ctx_stack: List[str] = []

    # don't cross into code that merely gets DEFINED under the lock
    def visit_FunctionDef(self, node):
        if not self.lock_stack:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        if not self.lock_stack:
            self.generic_visit(node)

    def visit_With(self, node):
        for i in node.items:        # context exprs: not yet under it
            self.visit(i.context_expr)
        lockish = [i for i in node.items
                   if _is_lockish(i.context_expr)]
        ctxs = [_unparse(i.context_expr) for i in node.items]
        self.lock_stack.extend(_unparse(i.context_expr)
                               for i in lockish)
        self.ctx_stack.extend(ctxs)
        for stmt in node.body:
            self.visit(stmt)
        if lockish:
            del self.lock_stack[-len(lockish):]
        del self.ctx_stack[-len(ctxs):]

    visit_AsyncWith = visit_With

    def visit_Call(self, node):
        self.generic_visit(node)
        if not self.lock_stack:
            return
        held = self.lock_stack[-1]
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        if name == "sleep" and isinstance(fn, ast.Attribute) \
                and "time" in _unparse(fn.value):
            self.out.append(Violation(
                "sleep-under-lock", self.rel, node.lineno,
                f"time.sleep inside `with {held}:` -- the holder "
                f"sleeps, every waiter starves"))
        elif name == "get" and isinstance(fn, ast.Attribute):
            kw = {k.arg for k in node.keywords}
            blocking_kw = not node.args and kw <= {"block", "timeout"}
            blocking_pos = (len(node.args) == 1 and not kw
                            and isinstance(node.args[0], ast.Constant)
                            and node.args[0].value is True)
            if blocking_kw or blocking_pos:
                self.out.append(Violation(
                    "sleep-under-lock", self.rel, node.lineno,
                    f"blocking dequeue `{_unparse(fn)}(...)` inside "
                    f"`with {held}:`"))
        elif name in ("wait", "join") and isinstance(fn, ast.Attribute) \
                and not node.args and not node.keywords:
            recv = _unparse(fn.value)
            if recv not in self.ctx_stack:
                self.out.append(Violation(
                    "sleep-under-lock", self.rel, node.lineno,
                    f"indefinite `{recv}.{name}()` inside "
                    f"`with {held}:` (a condvar may wait on its own "
                    f"lock; anything else blocks the holder forever)"))
        elif name in _DISPATCH_CALLS:
            self.out.append(Violation(
                "sleep-under-lock", self.rel, node.lineno,
                f"device dispatch `{name}(...)` inside `with {held}:`"
                f" -- a dispatch can burn a full watchdog deadline"))


def rule_sleep_under_lock(ctx: Ctx) -> List[Violation]:
    out: List[Violation] = []
    for rel, _text, tree in ctx.files:
        _UnderLockVisitor(rel, out).visit(tree)
    return out


def _finally_releases(try_node: ast.Try, recv: str) -> bool:
    for stmt in try_node.finalbody:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "release" \
                    and _unparse(node.func.value) == recv:
                return True
    return False


def rule_bare_acquire(ctx: Ctx) -> List[Violation]:
    out: List[Violation] = []

    def walk(rel: str, body: list, try_stack: list) -> None:
        for i, stmt in enumerate(body):
            if isinstance(stmt, ast.Expr) \
                    and isinstance(stmt.value, ast.Call) \
                    and isinstance(stmt.value.func, ast.Attribute) \
                    and stmt.value.func.attr == "acquire":
                recv = _unparse(stmt.value.func.value)
                ok = any(_finally_releases(t, recv) for t in try_stack)
                if not ok and i + 1 < len(body) \
                        and isinstance(body[i + 1], ast.Try) \
                        and _finally_releases(body[i + 1], recv):
                    ok = True
                if not ok:
                    out.append(Violation(
                        "bare-acquire", rel, stmt.lineno,
                        f"bare `{recv}.acquire()` without a try/finally"
                        f" releasing it -- an exception here wedges the"
                        f" lock forever"))
            for field in ("body", "orelse", "handlers", "finalbody"):
                sub = getattr(stmt, field, None)
                if not sub:
                    continue
                if field == "handlers":
                    for h in sub:
                        walk(rel, h.body, try_stack)
                    continue
                nested = try_stack
                if isinstance(stmt, ast.Try) and field in ("body",
                                                           "orelse"):
                    nested = try_stack + [stmt]
                walk(rel, sub, nested)

    for rel, _text, tree in ctx.files:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                walk(rel, node.body, [])
    return out


# ----------------------------------------------------------------------
# dispatch-hygiene rules (ISSUE 10)


class _JitSiteVisitor(ast.NodeVisitor):
    """no-callsite-jit: a ``jax.jit`` reference inside a function body
    is only allowed when some enclosing function is decorated with an
    ``lru_cache`` (the shape-bucket factory pattern); module level is
    always fine."""

    def __init__(self, rel: str, out: List[Violation]):
        self.rel = rel
        self.out = out
        self.fn_depth = 0
        self.lru_depth = 0

    def visit_FunctionDef(self, node):
        lru = any("lru_cache" in _unparse(d) or
                  _unparse(d).split("(")[0].endswith("cache")
                  for d in node.decorator_list)
        self.fn_depth += 1
        if lru:
            self.lru_depth += 1
        self.generic_visit(node)
        if lru:
            self.lru_depth -= 1
        self.fn_depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self.fn_depth += 1
        self.generic_visit(node)
        self.fn_depth -= 1

    def visit_Attribute(self, node):
        if (node.attr == "jit" and isinstance(node.ctx, ast.Load)
                and _unparse(node.value) == "jax"
                and self.fn_depth > 0 and self.lru_depth == 0):
            self.out.append(Violation(
                "no-callsite-jit", self.rel, node.lineno,
                "jax.jit constructed at a call site -- a fresh jit "
                "per call defeats the compile cache (steady-state "
                "retrace); hoist to module level or behind an "
                "lru_cache'd shape-bucket factory"))
        self.generic_visit(node)


def rule_no_callsite_jit(ctx: Ctx) -> List[Violation]:
    out: List[Violation] = []
    for rel, _text, tree in ctx.files:
        if rel.endswith(os.path.join("nomad_tpu", "jitcheck.py")):
            continue            # the patcher itself handles raw jit
        _JitSiteVisitor(rel, out).visit(tree)
    return out


# a function that calls any of these is a solver hot function: its
# body runs on (or stages for) the dispatch path
_HOT_MARKERS = {"device_put_cached", "_put_eval_sharded", "run_dispatch",
                "solve_lane_fused", "solve_lane_wave",
                "solve_lane_wave_preempt", "fuse_and_solve",
                "solve_groups", "solve_eval_batch",
                "solve_eval_batch_preempt", "mesh_solve_fn"}
_SYNC_ATTRS = {"device_get", "item", "block_until_ready"}


def _is_sanctioned_with(node: ast.With) -> bool:
    # matches both the bare marker and the tagged form the
    # fetch-accounted rule requires (sanctioned_fetch("wave"))
    return any(
        isinstance(i.context_expr, ast.Call)
        and _unparse(i.context_expr.func).endswith("sanctioned_fetch")
        for i in node.items)


class _HotSyncVisitor(ast.NodeVisitor):
    """Within ONE hot function body: flag device fetches outside a
    ``with jitcheck.sanctioned_fetch():`` block."""

    def __init__(self, rel: str, out: List[Violation]):
        self.rel = rel
        self.out = out
        self.sanct = 0

    def visit_FunctionDef(self, node):
        pass                    # nested defs get their own hot check

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node):
        sanct = _is_sanctioned_with(node)
        if sanct:
            self.sanct += 1
        self.generic_visit(node)
        if sanct:
            self.sanct -= 1

    visit_AsyncWith = visit_With

    def visit_Call(self, node):
        self.generic_visit(node)
        if self.sanct:
            return
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _SYNC_ATTRS:
            self.out.append(Violation(
                "no-host-sync-hot", self.rel, node.lineno,
                f"host sync `{_unparse(fn)}(...)` inside a solver hot "
                f"function -- each sync serializes the dispatch "
                f"pipeline; route through the one sanctioned bulk "
                f"fetch (`with jitcheck.sanctioned_fetch():`)"))


class _SyncUnderLockVisitor(ast.NodeVisitor):
    """Device fetches statically inside ``with <lock>:`` -- a fetch can
    burn a watchdog deadline while every peer waits on the lock."""

    def __init__(self, rel: str, out: List[Violation]):
        self.rel = rel
        self.out = out
        self.lock_depth = 0

    def visit_FunctionDef(self, node):
        if not self.lock_depth:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node):
        for i in node.items:
            self.visit(i.context_expr)
        lockish = sum(1 for i in node.items
                      if _is_lockish(i.context_expr))
        self.lock_depth += lockish
        for stmt in node.body:
            self.visit(stmt)
        self.lock_depth -= lockish

    visit_AsyncWith = visit_With

    def visit_Call(self, node):
        self.generic_visit(node)
        if not self.lock_depth:
            return
        fn = node.func
        if isinstance(fn, ast.Attribute) and \
                fn.attr in ("device_get", "item"):
            self.out.append(Violation(
                "no-host-sync-hot", self.rel, node.lineno,
                f"device fetch `{_unparse(fn)}(...)` inside a "
                f"`with <lock>:` block -- the holder blocks on the "
                f"device while every waiter starves"))


def rule_no_host_sync_hot(ctx: Ctx) -> List[Violation]:
    out: List[Violation] = []
    solver_dirs = (os.path.join("nomad_tpu", "solver"),
                   os.path.join("nomad_tpu", "parallel"))
    for rel, _text, tree in ctx.files:
        if rel.startswith(solver_dirs):
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                calls = {
                    (c.func.attr if isinstance(c.func, ast.Attribute)
                     else c.func.id if isinstance(c.func, ast.Name)
                     else "")
                    for c in ast.walk(node)
                    if isinstance(c, ast.Call)}
                if not calls & _HOT_MARKERS:
                    continue
                v = _HotSyncVisitor(rel, out)
                for stmt in node.body:
                    v.visit(stmt)
        _SyncUnderLockVisitor(rel, out).visit(tree)
    # a fetch can be flagged by both the hot-function and under-lock
    # scans; one report per line is enough
    seen: set = set()
    deduped = []
    for v in out:
        key = (v.path, v.line)
        if key not in seen:
            seen.add(key)
            deduped.append(v)
    return deduped


_F64_LITERALS = {"jnp.float64", "np.float64", "jax.numpy.float64"}


def rule_dtype_threaded(ctx: Ctx) -> List[Violation]:
    out: List[Violation] = []
    kernel_dirs = (os.path.join("nomad_tpu", "solver"),
                   os.path.join("nomad_tpu", "parallel"))
    for rel, _text, tree in ctx.files:
        if not rel.startswith(kernel_dirs):
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load) \
                    and _unparse(node) == "jnp.float64":
                out.append(Violation(
                    "dtype-threaded", rel, node.lineno,
                    "bare jnp.float64 in device-kernel code -- thread "
                    "the dtype through the kernel's static "
                    "`dtype_name` arg (f64 is emulated on TPU)"))
            elif isinstance(node, ast.Call):
                recv = _unparse(node.func)
                if not recv.startswith(("jnp.", "jax.numpy.")):
                    continue
                for kw in node.keywords:
                    if kw.arg != "dtype":
                        continue
                    val = _unparse(kw.value)
                    lit = (isinstance(kw.value, ast.Constant)
                           and kw.value.value == "float64")
                    if lit or val in _F64_LITERALS:
                        out.append(Violation(
                            "dtype-threaded", rel, node.lineno,
                            f"float64 dtype literal in `{recv}(...)` "
                            f"-- thread the dtype through the static "
                            f"`dtype_name` arg"))
    # a `jnp.zeros(..., dtype=jnp.float64)` call trips both scans --
    # one report per line is enough
    seen: set = set()
    deduped = []
    for v in out:
        key = (v.path, v.line)
        if key not in seen:
            seen.add(key)
            deduped.append(v)
    return deduped


_FREEZE_CALLS = {"_freeze", "setflags", "freeze_matrix",
                 "freeze_usage_base", "note_frozen", "_note_frozen",
                 "_set_writeable"}
_MEMOISH_TAIL = re.compile(r"(memos?$)|(^_?[A-Z0-9_]*CACHE$)")


def _memoish_subscript(target) -> Optional[str]:
    """The store-target name when ``target`` is a subscript into a
    memo/cache container (``memo[k] = v``, ``_X_CACHE[k] = v``)."""
    if not isinstance(target, ast.Subscript):
        return None
    base = _unparse(target.value)
    tail = base.split(".")[-1]
    if _MEMOISH_TAIL.search(tail):
        return base
    return None


def rule_frozen_memo(ctx: Ctx) -> List[Violation]:
    out: List[Violation] = []
    for rel, _text, tree in ctx.files:
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            # innermost wins: don't re-scan nested defs from the outer
            body_nodes = []
            stack = list(fn.body)
            has_freeze = False
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                body_nodes.append(node)
                if isinstance(node, ast.Call):
                    name = (node.func.attr
                            if isinstance(node.func, ast.Attribute)
                            else node.func.id
                            if isinstance(node.func, ast.Name) else "")
                    if name in _FREEZE_CALLS:
                        has_freeze = True
                stack.extend(ast.iter_child_nodes(node))
            if has_freeze:
                continue
            for node in body_nodes:
                if not isinstance(node, ast.Assign):
                    continue
                for target in node.targets:
                    base = _memoish_subscript(target)
                    if base is not None:
                        out.append(Violation(
                            "frozen-memo", rel, node.lineno,
                            f"array stored into `{base}[...]` without "
                            f"a freeze -- memoized payloads are "
                            f"shared across evals and must be "
                            f"writeable=False (jitcheck invariant)"))
    return out


def rule_fetch_accounted(ctx: Ctx) -> List[Violation]:
    """Every ``sanctioned_fetch(...)`` context manager carries a
    non-empty string-literal ledger tag naming the transport: the
    transfer observatory (solver/xferobs.py) decomposes fetched result
    bytes by that tag, so an untagged site is a payload the ledger
    cannot attribute."""
    out: List[Violation] = []
    for rel, _text, tree in ctx.files:
        if rel.endswith(os.path.join("nomad_tpu", "jitcheck.py")):
            continue            # the marker's own definition/dispatch
        for node in ast.walk(tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                ce = item.context_expr
                if not (isinstance(ce, ast.Call)
                        and _unparse(ce.func).endswith(
                            "sanctioned_fetch")):
                    continue
                arg = ce.args[0] if ce.args else None
                ok = (isinstance(arg, ast.Constant)
                      and isinstance(arg.value, str) and arg.value)
                if not ok:
                    out.append(Violation(
                        "fetch-accounted", rel, ce.lineno,
                        "sanctioned_fetch() without a string-literal "
                        "ledger tag -- pass the transport name "
                        "(e.g. sanctioned_fetch(\"wave\")) so the "
                        "transfer ledger can attribute the fetched "
                        "bytes"))
    return out


# ----------------------------------------------------------------------
# store-discipline rules (ISSUE 11)

# AllocTable mutators; calling one on an alloc_table receiver outside
# nomad_tpu/state/ bypasses the store's locked write API
_TABLE_MUTATORS = {"upsert", "upsert_many", "remove", "register_node",
                   "compact", "preallocate", "_grow", "_fold_inc_build",
                   "_fold_inc_row", "_fold_inc_rows"}
# store-internal table dicts; subscript/attr writes to these outside
# state/ are direct index corruption. The receiver must look like a
# store/state handle: brokers and trackers own private dicts with the
# same names (broker self._evals) that are theirs to write.
_STORE_INTERNALS = re.compile(
    r"(?:store|state)\w*\._(allocs|nodes|jobs|evals|deployments|"
    r"allocs_by_node|allocs_by_job|table_index|alloc_deltas)\b")
_STATE_DIR = os.path.join("nomad_tpu", "state")


def _is_table_recv(expr: ast.AST) -> bool:
    s = _unparse(expr)
    return "alloc_table" in s or s in ("table", "t", "tbl")


def rule_no_direct_table_write(ctx: Ctx) -> List[Violation]:
    out: List[Violation] = []
    for rel, _text, tree in ctx.files:
        if rel.startswith(_STATE_DIR) or \
                rel.endswith(os.path.join("nomad_tpu", "statecheck.py")):
            continue            # the owner and its sanitizer
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _TABLE_MUTATORS \
                    and _is_table_recv(node.func.value):
                out.append(Violation(
                    "no-direct-table-write", rel, node.lineno,
                    f"AllocTable mutator "
                    f"`{_unparse(node.func)}(...)` outside "
                    f"nomad_tpu/state/ -- table writes go through the "
                    f"store's locked write API (upsert_allocs / "
                    f"upsert_plan_results / compact_alloc_table)"))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    s = _unparse(t)
                    if ".alloc_table." in s or _STORE_INTERNALS.search(s):
                        out.append(Violation(
                            "no-direct-table-write", rel, node.lineno,
                            f"store/table internals written directly "
                            f"(`{s} = ...`) outside nomad_tpu/state/"))
    return out


_MEMO_NAME = re.compile(r"(memo|cache)", re.IGNORECASE)
_VERSION_WORDS = re.compile(
    r"version|index|token|fingerprint|\bfp\b|digest|snapshot|hash")
# module dirs whose caches derive from store state (jobspec/structs
# codecs are content-keyed and out of scope)
_STORE_DERIVED_DIRS = (os.path.join("nomad_tpu", "solver"),
                       os.path.join("nomad_tpu", "tensor"),
                       os.path.join("nomad_tpu", "server"))


def _key_mentions_version(fn: ast.AST, key_node: ast.AST) -> bool:
    """Whether the memo key expression (or, for a plain Name, any
    assignment to it inside the same function) carries a table
    version/index/token/fingerprint component."""
    if _VERSION_WORDS.search(_unparse(key_node)):
        return True
    if isinstance(key_node, ast.Name):
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == key_node.id
                    for t in sub.targets):
                if _VERSION_WORDS.search(_unparse(sub.value)):
                    return True
    return False


def _is_call_scoped(fn: ast.AST, base_node: ast.AST) -> bool:
    """A container freshly bound to a dict literal inside the same
    function is call-scoped (a per-call lookup memo like service.py's
    node_cache), not a cross-call cache -- staleness dies with the
    frame."""
    if not isinstance(base_node, ast.Name):
        return False
    for sub in ast.walk(fn):
        if isinstance(sub, (ast.Assign, ast.AnnAssign)):
            targets = (sub.targets if isinstance(sub, ast.Assign)
                       else [sub.target])
            value = sub.value
            if value is None:
                continue
            if any(isinstance(t, ast.Name) and t.id == base_node.id
                   for t in targets):
                if isinstance(value, ast.Dict) or (
                        isinstance(value, ast.Call)
                        and _unparse(value.func) in ("dict",
                                                     "OrderedDict",
                                                     "defaultdict")):
                    return True
    return False


def rule_version_keyed_memo(ctx: Ctx) -> List[Violation]:
    out: List[Violation] = []
    for rel, _text, tree in ctx.files:
        if not rel.startswith(_STORE_DERIVED_DIRS):
            continue
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Assign):
                    continue
                for target in node.targets:
                    if isinstance(target, ast.Subscript):
                        base = _unparse(target.value)
                        tail = base.split(".")[-1]
                        if not _MEMO_NAME.search(tail):
                            continue
                        if _key_mentions_version(fn, target.slice):
                            continue
                        # the version token may ride the ENTRY instead
                        # of the key when the hit path checks it
                        # (usage-base memos store (store, token, base))
                        if _VERSION_WORDS.search(_unparse(node.value)):
                            continue
                        if _is_call_scoped(fn, target.value):
                            continue
                        out.append(Violation(
                            "version-keyed-memo", rel, node.lineno,
                            f"store-derived cache `{base}[...]` keyed "
                            f"without a table version/index/token/"
                            f"fingerprint component -- a content-blind "
                            f"key serves stale state after the next "
                            f"table write"))
                    elif isinstance(target, ast.Attribute) \
                            and _MEMO_NAME.search(target.attr):
                        if _VERSION_WORDS.search(_unparse(node.value)):
                            continue
                        out.append(Violation(
                            "version-keyed-memo", rel, node.lineno,
                            f"store-derived memo attribute "
                            f"`{_unparse(target)}` assigned without a "
                            f"version/index/token component in the "
                            f"cached value"))
    return out


_SNAPSHOT_CALL = re.compile(r"(state|store|_store)\w*\.snapshot\(\)")


def rule_no_snapshot_escape(ctx: Ctx) -> List[Violation]:
    out: List[Violation] = []
    for rel, _text, tree in ctx.files:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign):
                continue
            if not _SNAPSHOT_CALL.search(_unparse(node.value)):
                continue
            for target in node.targets:
                s = _unparse(target)
                if not (isinstance(target, ast.Attribute)
                        and s.startswith("self.")):
                    continue
                out.append(Violation(
                    "no-snapshot-escape", rel, node.lineno,
                    f"state snapshot stored into long-lived attribute "
                    f"`{s}` -- snapshots are per-eval consistency "
                    f"windows; holding one pins every object of its "
                    f"generation and serves stale reads forever"))
        # module-level globals: snapshot call in a top-level assign
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and \
                    _SNAPSHOT_CALL.search(_unparse(stmt.value)):
                out.append(Violation(
                    "no-snapshot-escape", rel, stmt.lineno,
                    f"state snapshot bound to module global "
                    f"`{_unparse(stmt.targets[0])}`"))
    return out


# ----------------------------------------------------------------------
# schedule-hygiene rules (ISSUE 12, the static complement of the
# deterministic schedule explorer nomad_tpu/schedcheck.py)

_SHUTDOWNISH = re.compile(
    r"shutdown|stop|close|teardown|drain|destroy|reap|finalize|"
    r"cleanup|__exit__|join|wait", re.IGNORECASE)
_EVENTISH = re.compile(
    r"(?:event|stop|stopped|done|ready|started|kill|exit)$",
    re.IGNORECASE)
_PROCISH = re.compile(r"(?:proc|process|popen)\w*$", re.IGNORECASE)


def rule_join_with_timeout(ctx: Ctx) -> List[Violation]:
    """No indefinite ``Thread.join()`` / ``Event.wait()`` outside
    shutdown paths: an argless join/wait on a wedged thread turns one
    stuck eval into an invisible control-plane wedge -- a bounded
    ``while t.is_alive(): t.join(timeout=...)`` keeps the stall
    observable (and gives schedcheck an interposition point)."""
    out: List[Violation] = []
    for rel, _text, tree in ctx.files:
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if _SHUTDOWNISH.search(fn.name):
                continue            # shutdown paths may drain forever
            for node in ast.walk(fn):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and node is not fn:
                    continue
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and not node.args and not node.keywords):
                    continue
                recv = _unparse(node.func.value)
                tail = recv.split(".")[-1]
                if node.func.attr == "join":
                    if _PROCISH.search(tail):
                        continue    # subprocess reaps are not threads
                    out.append(Violation(
                        "join-with-timeout", rel, node.lineno,
                        f"indefinite `{recv}.join()` outside a "
                        f"shutdown path -- a wedged thread hangs the "
                        f"caller invisibly; use a bounded "
                        f"`while t.is_alive(): t.join(timeout=...)`"))
                elif node.func.attr == "wait" and \
                        _EVENTISH.search(tail):
                    out.append(Violation(
                        "join-with-timeout", rel, node.lineno,
                        f"indefinite `{recv}.wait()` outside a "
                        f"shutdown path -- an unset event parks the "
                        f"caller forever; pass a timeout and re-check"))
    return out


def rule_no_sleep_sync(ctx: Ctx) -> List[Violation]:
    """tests/ may not synchronize threads via bare ``time.sleep`` in a
    test body: "sleep and hope the worker got there" is the #1 source
    of 1-core flakes.  Poll loops (sleep inside while/for, wait_until)
    and simulated-work stubs (sleep inside a nested def) are fine --
    the rule flags straight-line sleeps in ``test_*`` bodies only."""
    out: List[Violation] = []
    tdir = os.path.join(ctx.root, "tests")
    if not os.path.isdir(tdir):
        return out
    for name in sorted(os.listdir(tdir)):
        if not name.endswith(".py"):
            continue
        rel = f"tests/{name}"
        try:
            with open(os.path.join(tdir, name), encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=rel)
        except (OSError, SyntaxError):
            continue                # tier-1 collection owns this
        for fn in ast.walk(tree):
            if not (isinstance(fn, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))
                    and fn.name.startswith("test_")):
                continue

            def walk(node, in_loop):
                for ch in ast.iter_child_nodes(node):
                    if isinstance(ch, (ast.FunctionDef,
                                       ast.AsyncFunctionDef,
                                       ast.Lambda)):
                        continue    # nested stubs simulate work
                    loop = in_loop or isinstance(
                        node, (ast.While, ast.For))
                    if isinstance(ch, ast.Call) \
                            and isinstance(ch.func, ast.Attribute) \
                            and ch.func.attr == "sleep" \
                            and _unparse(ch.func.value) \
                            .split(".")[-1].endswith("time") \
                            and not loop:
                        out.append(Violation(
                            "no-sleep-sync", rel, ch.lineno,
                            f"bare `{_unparse(ch.func)}"
                            f"({_unparse(ch.args[0]) if ch.args else ''})`"
                            f" in a test body synchronizes threads by "
                            f"wall clock -- the #1 source of 1-core "
                            f"flakes; poll a predicate (wait_until) or "
                            f"use an event/condition"))
                    walk(ch, loop)

            walk(fn, False)
    return out


def rule_daemon_declared(ctx: Ctx) -> List[Violation]:
    """Every repo ``threading.Thread(...)`` sets ``daemon=``
    explicitly: daemon-ness is inherited from the CREATOR by default,
    so the same spawn site produces a process-pinning non-daemon
    thread or a silently-killed daemon depending on who called it."""
    out: List[Violation] = []
    for rel, _text, tree in ctx.files:
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and _unparse(node.func) in ("threading.Thread",
                                                "Thread")):
                continue
            if any(k.arg == "daemon" for k in node.keywords):
                continue
            out.append(Violation(
                "daemon-declared", rel, node.lineno,
                "threading.Thread(...) without an explicit daemon= -- "
                "daemon-ness inherits from the creator, so this spawn "
                "site's shutdown behavior depends on who calls it"))
    return out


def rule_delta_carried(ctx: Ctx) -> List[Violation]:
    out: List[Violation] = []
    for rel, _text, tree in ctx.files:
        if not rel.startswith(_STATE_DIR):
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "_bump"):
                continue
            touches_allocs = any(
                (isinstance(a, ast.Constant) and a.value == "allocs")
                or isinstance(a, ast.Starred)   # _bump(*TABLES)
                for a in node.args)
            if not touches_allocs:
                continue
            if any(k.arg == "delta" for k in node.keywords):
                continue
            out.append(Violation(
                "delta-carried", rel, node.lineno,
                f"`{_unparse(node.func)}(\"allocs\", ...)` without "
                f"`delta=` -- the journal entry is an uncoverable gap "
                f"and every incremental-memo holder refolds wholesale "
                f"(pass the (old, new) pairs or waive with the reason "
                f"the write is wholesale by design)"))
    return out


# ----------------------------------------------------------------------
# shard-hygiene rules (ISSUE 15)

_PARALLEL_DIR = os.path.join("nomad_tpu", "parallel") + os.sep
# the runtime sanitizer inspects shardings (it never constructs puts)
# and is allowed to name the classes it audits
_SHARDCHECK_FILE = os.path.join("nomad_tpu", "shardcheck.py")

_SHARDING_CLASSES = ("PartitionSpec", "NamedSharding", "Mesh")


def _sharding_aliases(tree: ast.AST) -> Dict[str, str]:
    """Local names bound to jax.sharding classes in this module
    (``from jax.sharding import PartitionSpec as P`` binds P), so the
    rules catch the repo's aliasing idiom, not just the full names."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                node.module.startswith("jax.sharding"):
            for alias in node.names:
                if alias.name in _SHARDING_CLASSES:
                    out[alias.asname or alias.name] = alias.name
    return out


def _called_sharding_class(node: ast.Call,
                           aliases: Dict[str, str]) -> Optional[str]:
    """The jax.sharding class a Call constructs, or None: a direct
    alias call (``P(...)``) or an attribute chain ending in one
    (``jax.sharding.NamedSharding(...)``)."""
    f = node.func
    if isinstance(f, ast.Name):
        return aliases.get(f.id)
    if isinstance(f, ast.Attribute) and f.attr in _SHARDING_CLASSES:
        recv = _unparse(f.value)
        if recv.endswith("sharding") or recv == "jax":
            return f.attr
    return None


def _shard_rule_scans(rel: str) -> bool:
    return not (rel.startswith(_PARALLEL_DIR)
                or rel == _SHARDCHECK_FILE)


def rule_spec_declared(ctx: Ctx) -> List[Violation]:
    out: List[Violation] = []
    for rel, _text, tree in ctx.files:
        if not _shard_rule_scans(rel):
            continue
        aliases = _sharding_aliases(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            cls = _called_sharding_class(node, aliases)
            if cls in ("PartitionSpec", "NamedSharding"):
                out.append(Violation(
                    "spec-declared", rel, node.lineno,
                    f"`{cls}(...)` constructed outside "
                    f"nomad_tpu/parallel/ -- sharding intent lives in "
                    f"the parallel/mesh.py spec registry "
                    f"(SPEC_GROUPS/declared_specs); an inline spec is "
                    f"a contract shardcheck never compares against"))
    return out


def rule_mesh_factory(ctx: Ctx) -> List[Violation]:
    out: List[Violation] = []
    for rel, _text, tree in ctx.files:
        if not _shard_rule_scans(rel):
            continue
        aliases = _sharding_aliases(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _called_sharding_class(node, aliases) == "Mesh":
                out.append(Violation(
                    "mesh-factory", rel, node.lineno,
                    f"`Mesh(...)` constructed outside the parallel/ "
                    f"factories -- build meshes via make_mesh/"
                    f"pick_mesh/eval_axis_mesh so the topology stays "
                    f"one lru-cache-keyed artifact the spec registry "
                    f"declares against"))
    return out


def rule_no_implicit_put(ctx: Ctx) -> List[Violation]:
    out: List[Violation] = []
    for rel, _text, tree in ctx.files:
        if not _shard_rule_scans(rel):
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, (ast.Name, ast.Attribute))):
                continue
            name = (node.func.id if isinstance(node.func, ast.Name)
                    else node.func.attr)
            if name != "device_put":
                continue
            shard_args = [a for a in node.args[1:]] + [
                k.value for k in node.keywords
                if k.arg in ("device", "sharding", "out_shardings")]
            if any(re.search(r"[Ss]harding", _unparse(a))
                   for a in shard_args):
                out.append(Violation(
                    "no-implicit-put", rel, node.lineno,
                    f"`device_put` with a sharding argument outside "
                    f"nomad_tpu/parallel/ -- route sharded uploads "
                    f"through shard_solver_inputs/shard_eval_axis (or "
                    f"device_put_cached for unsharded buffers) so the "
                    f"transfer ledger's per-shard rows see them"))
    return out


AST_RULES = {
    "fire-registered": rule_fire_registered,
    "killswitch-tested": rule_killswitch_tested,
    "telemetry": rule_telemetry,           # emits -literal and -kind
    "sleep-under-lock": rule_sleep_under_lock,
    "bare-acquire": rule_bare_acquire,
    "no-callsite-jit": rule_no_callsite_jit,
    "no-host-sync-hot": rule_no_host_sync_hot,
    "dtype-threaded": rule_dtype_threaded,
    "frozen-memo": rule_frozen_memo,
    "fetch-accounted": rule_fetch_accounted,
    "no-direct-table-write": rule_no_direct_table_write,
    "version-keyed-memo": rule_version_keyed_memo,
    "no-snapshot-escape": rule_no_snapshot_escape,
    "delta-carried": rule_delta_carried,
    "join-with-timeout": rule_join_with_timeout,
    "no-sleep-sync": rule_no_sleep_sync,
    "daemon-declared": rule_daemon_declared,
    "spec-declared": rule_spec_declared,
    "mesh-factory": rule_mesh_factory,
    "no-implicit-put": rule_no_implicit_put,
}
# ids a violation may carry (for --rule selection and waiver matching)
RULE_IDS = ("fire-registered", "killswitch-tested", "telemetry-literal",
            "telemetry-kind", "sleep-under-lock", "bare-acquire",
            "no-callsite-jit", "no-host-sync-hot", "dtype-threaded",
            "frozen-memo", "fetch-accounted", "no-direct-table-write",
            "version-keyed-memo",
            "no-snapshot-escape", "delta-carried", "join-with-timeout",
            "no-sleep-sync", "daemon-declared", "spec-declared",
            "mesh-factory", "no-implicit-put")

LEGACY_RULES = ("metrics-doc", "knob-doc", "bench-regress")


# ----------------------------------------------------------------------
# waivers + driver


def _load_legacy(name: str):
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"check_{name.replace('-', '_')}.py")
    spec = importlib.util.spec_from_file_location(
        f"_nomadlint_{name}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_legacy(name: str, argv: List[str]) -> int:
    mod = _load_legacy(name)
    try:
        if name == "bench-regress":
            return mod.main(argv or [])
        return mod.main()
    except SystemExit as e:         # legacy argparse usage errors
        return int(e.code or 0)


def apply_waivers(root: str, violations: List[Violation],
                  used: Optional[set] = None
                  ) -> Tuple[List[Violation], int]:
    """Drop violations waived at the site (or the line above) with a
    justified `# nomadlint: waive=<rule> -- reason` comment.  When
    ``used`` is provided, every (path, line, rule) whose waiver comment
    actually suppressed something is recorded into it -- the --stats
    stale-waiver inventory is the complement of that set."""
    kept: List[Violation] = []
    waived = 0
    lines_cache: Dict[str, List[str]] = {}
    for v in violations:
        path = os.path.join(root, v.path)
        lines = lines_cache.get(path)
        if lines is None:
            try:
                with open(path, encoding="utf-8") as f:
                    lines = f.read().splitlines()
            except OSError:
                lines = []
            lines_cache[path] = lines
        def _line_waives(ln: int) -> bool:
            if not 1 <= ln <= len(lines):
                return False
            m = _WAIVER.search(lines[ln - 1])
            ok = bool(m and v.rule in m.group(1).split(","))
            if ok and used is not None:
                used.add((v.path, ln, v.rule))
            return ok

        # the violating line, then the contiguous comment block above
        # it (multi-line justifications are the norm)
        hit = _line_waives(v.line)
        ln = v.line - 1
        while not hit and 1 <= ln <= len(lines) \
                and lines[ln - 1].lstrip().startswith("#"):
            hit = _line_waives(ln)
            ln -= 1
        if hit:
            waived += 1
        else:
            kept.append(v)
    return kept, waived


def collect_waiver_comments(root: str) -> List[Tuple[str, int, str]]:
    """Every ``nomadlint: waive=<rules>`` comment in the scanned tree
    (nomad_tpu/ + bench.py + tests/, which no-sleep-sync lints) as
    (rel_path, line, rule) triples -- one per rule id the comment
    names."""
    out: List[Tuple[str, int, str]] = []
    scan = []
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        scan.append(bench)
    for sub in ("nomad_tpu", "tests"):
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(root, sub)):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            scan.extend(os.path.join(dirpath, f)
                        for f in sorted(filenames)
                        if f.endswith(".py"))
    for path in scan:
        rel = os.path.relpath(path, root)
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        for i, line in enumerate(lines, 1):
            m = _WAIVER.search(line)
            if not m:
                continue
            for rule in m.group(1).split(","):
                out.append((rel, i, rule))
    return out


def _rule_scans(path: str, rule: str) -> bool:
    """Whether ``rule`` scans ``path`` at all: a waiver can only be
    stale where its rule could fire (tests/ is linted only by
    no-sleep-sync; a lint-fixture string under tests/ that happens to
    contain a waiver comment for a code rule is not a stale waiver)."""
    in_tests = path.replace(os.sep, "/").startswith("tests/")
    if rule == "no-sleep-sync":
        return in_tests
    return not in_tests


def run_stats(root: str, rules: List[str]) -> Tuple[dict, List[tuple]]:
    """--stats: per-rule fired/waived counts plus the stale-waiver
    inventory (waiver comments that no longer suppress anything on
    their line -- removable)."""
    ctx = Ctx(root)
    violations = list(ctx.parse_errors)
    for key, fn in AST_RULES.items():
        ids = (("telemetry-literal", "telemetry-kind")
               if key == "telemetry" else (key,))
        if not any(r in rules for r in ids):
            continue
        violations.extend(v for v in fn(ctx) if v.rule in rules)
    used: set = set()
    kept, _waived = apply_waivers(root, violations, used=used)
    fired: Dict[str, int] = {r: 0 for r in rules}
    kept_counts: Dict[str, int] = {r: 0 for r in rules}
    for v in violations:
        fired[v.rule] = fired.get(v.rule, 0) + 1
    for v in kept:
        kept_counts[v.rule] = kept_counts.get(v.rule, 0) + 1
    waived_by_rule = {r: fired.get(r, 0) - kept_counts.get(r, 0)
                      for r in fired}
    comments = collect_waiver_comments(root)
    used_lines = {(p, ln) for (p, ln, _r) in used}
    stale = [(p, ln, rule) for (p, ln, rule) in comments
             if rule in rules and (p, ln) not in used_lines
             and _rule_scans(p, rule)]
    stats = {"fired": fired, "waived": waived_by_rule,
             "kept": len(kept), "waiver_comments": len(comments)}
    return stats, stale


def to_sarif(violations: List[Violation], rules: List[str]) -> dict:
    """SARIF 2.1.0 document for CI/editor annotation surfaces: one run,
    one driver (nomadlint), one result per kept violation."""
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0"
                    ".json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "nomadlint",
                "informationUri":
                    "https://github.com/nomad-tpu/nomad-tpu",
                "rules": [{"id": r} for r in sorted(set(rules))],
            }},
            "results": [{
                "ruleId": v.rule,
                "level": "error",
                "message": {"text": v.msg},
                "locations": [{"physicalLocation": {
                    "artifactLocation": {
                        "uri": v.path.replace(os.sep, "/")},
                    "region": {"startLine": max(1, v.line)},
                }}],
            } for v in sorted(violations,
                              key=lambda v: (v.path, v.line))],
        }],
    }


def fix_stale_waivers(root: str, rules: List[str],
                      apply: bool = False) -> List[Tuple[str, int]]:
    """Delete waiver comment lines whose every named rule no longer
    fires on their line (the --stats removable inventory).  Dry-run by
    default: returns the (path, line) list; ``apply=True`` rewrites
    the files.  A comment naming several rules is only removed when
    ALL of them are stale there."""
    _stats, stale = run_stats(root, rules)
    stale_set = {(p, ln, r) for (p, ln, r) in stale}
    by_line: Dict[Tuple[str, int], List[str]] = {}
    for (p, ln, r) in collect_waiver_comments(root):
        by_line.setdefault((p, ln), []).append(r)
    removable = sorted(
        (p, ln) for (p, ln), rs in by_line.items()
        if all(r in rules and (p, ln, r) in stale_set for r in rs))
    if not apply:
        return removable
    by_file: Dict[str, List[int]] = {}
    for p, ln in removable:
        by_file.setdefault(p, []).append(ln)
    for p, lns in by_file.items():
        path = os.path.join(root, p)
        try:
            with open(path, encoding="utf-8") as f:
                lines = f.read().splitlines(keepends=True)
        except OSError:
            continue
        for ln in sorted(lns, reverse=True):
            if not 1 <= ln <= len(lines):
                continue
            text = lines[ln - 1]
            if text.lstrip().startswith("#"):
                del lines[ln - 1]       # whole-line waiver comment
            else:
                # trailing waiver on a code line: strip the comment
                lines[ln - 1] = re.sub(
                    r"\s*#\s*nomadlint:.*$", "",
                    text.rstrip("\n")) + (
                        "\n" if text.endswith("\n") else "")
        with open(path, "w", encoding="utf-8") as f:
            f.writelines(lines)
    return removable


def run_ast_rules(root: str, rules: List[str]) -> Tuple[List[Violation],
                                                        int]:
    ctx = Ctx(root)
    violations = list(ctx.parse_errors)
    for key, fn in AST_RULES.items():
        ids = (("telemetry-literal", "telemetry-kind")
               if key == "telemetry" else (key,))
        if not any(r in rules for r in ids):
            continue
        violations.extend(v for v in fn(ctx) if v.rule in rules)
    return apply_waivers(root, violations)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="nomadlint",
        description="repo-invariant lint driver (see module docstring)")
    p.add_argument("--root", default=ROOT,
                   help="repo root to lint (fixture tests point this "
                   "at a synthetic tree)")
    p.add_argument("--rule", action="append", default=[],
                   help="run only this rule id (repeatable); default: "
                   "all AST rules + metrics-doc + knob-doc")
    p.add_argument("--list", action="store_true",
                   help="list rule ids and exit")
    p.add_argument("--stats", action="store_true",
                   help="per-rule fire/waiver inventory + stale-waiver "
                   "detection (a waiver whose rule no longer fires on "
                   "its line is removable); exit 1 when stale waivers "
                   "exist")
    p.add_argument("--sarif", metavar="PATH", default=None,
                   help="also write the kept violations as SARIF "
                   "2.1.0 to PATH ('-' = stdout) for CI/editor "
                   "annotations")
    p.add_argument("--fix-stale-waivers", action="store_true",
                   help="delete waiver comment lines --stats flags as "
                   "removable; DRY-RUN by default (lists them), pass "
                   "--apply to rewrite the files")
    p.add_argument("--apply", action="store_true",
                   help="with --fix-stale-waivers: actually rewrite")
    p.add_argument("rest", nargs="*",
                   help="extra argv for legacy rules (bench-regress "
                   "artifact)")
    args = p.parse_args(argv)

    if args.fix_stale_waivers:
        rules = [r for r in (args.rule or list(RULE_IDS))
                 if r in RULE_IDS]
        removed = fix_stale_waivers(args.root, rules, apply=args.apply)
        verb = "removed" if args.apply else "would remove (dry-run; " \
            "pass --apply to rewrite)"
        for path, line in removed:
            print(f"  {path}:{line}")
        print(f"fix-stale-waivers: {len(removed)} waiver line(s) "
              f"{verb}")
        return 0

    if args.stats:
        rules = args.rule or list(RULE_IDS)
        ast_rules = [r for r in rules if r in RULE_IDS]
        stats, stale = run_stats(args.root, ast_rules)
        print(f"{'rule':24s} {'fired':>6s} {'waived':>7s} {'kept':>5s}")
        for r in ast_rules:
            f = stats["fired"].get(r, 0)
            w = stats["waived"].get(r, 0)
            print(f"{r:24s} {f:6d} {w:7d} {f - w:5d}")
        print(f"waiver comments in tree: {stats['waiver_comments']}")
        if stale:
            print(f"\nstale waivers (rule no longer fires on that "
                  f"line -- removable): {len(stale)}")
            for path, line, rule in stale:
                print(f"  {path}:{line}: waive={rule}")
            return 1
        print("no stale waivers")
        return 0

    if args.list:
        for r in RULE_IDS:
            print(r)
        for r in LEGACY_RULES:
            print(f"{r} (legacy: scripts/check_"
                  f"{r.replace('-', '_')}.py)")
        return 0

    known = set(RULE_IDS) | set(LEGACY_RULES)
    for r in args.rule:
        if r not in known:
            print(f"unknown rule {r!r} (see --list)")
            return 2
    selected = args.rule or (list(RULE_IDS) + ["metrics-doc",
                                               "knob-doc"])

    rc = 0
    ast_selected = [r for r in selected if r in RULE_IDS]
    if ast_selected:
        kept, waived = run_ast_rules(args.root, ast_selected)
        for v in sorted(kept, key=lambda v: (v.path, v.line)):
            print(f"{v.path}:{v.line}: [{v.rule}] {v.msg}")
        note = f" ({waived} waived)" if waived else ""
        if kept:
            print(f"nomadlint: {len(kept)} violation(s){note}")
            rc = 1
        else:
            print(f"nomadlint: AST rules clean{note} "
                  f"[{', '.join(ast_selected)}]")
        if args.sarif:
            import json
            doc = to_sarif(kept, ast_selected)
            if args.sarif == "-":
                print(json.dumps(doc, indent=2))
            else:
                with open(args.sarif, "w", encoding="utf-8") as f:
                    json.dump(doc, f, indent=2)
                print(f"nomadlint: SARIF written to {args.sarif} "
                      f"({len(kept)} result(s))")
    for name in LEGACY_RULES:
        if name not in selected:
            continue
        if args.root != ROOT:
            print(f"nomadlint: skipping legacy rule {name} under "
                  f"--root (it scans the real repo)")
            continue
        lrc = run_legacy(name, args.rest or None)
        if lrc:
            print(f"nomadlint: legacy rule {name} failed (rc={lrc})")
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
