"""Cost of the block-merge step's selection primitive on chip: times a
while-loop of NBLK sequential steps, each doing a (B*K,) multi-key sort
/ top_k over vmapped E lanes -- the candidate structure of the block
kernel. If a sort step costs ~<=150us, block-merge wins (250 steps vs
2048 x 33us)."""
import statistics
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

E, B, K = 32, 32, 32
NBLK = 250

key = jax.random.PRNGKey(0)
eff = jax.random.uniform(key, (E, B * K), dtype=jnp.float32)
order = jax.random.randint(key, (E, B * K), 0, 64, dtype=jnp.int32)
midx = jnp.tile(jnp.arange(B * K, dtype=jnp.int32) % K, (E, 1))


def timeit(name, fn, *args):
    f = jax.jit(fn)
    out = f(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        out = f(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    med = statistics.median(ts)
    print(f"{name:<34} {med*1000:8.2f}ms total  {med/NBLK*1e6:7.1f}us/step",
          flush=True)


def loop_sort3(eff, order, midx):
    def one(effl, orderl, midxl):
        def body(carry, _):
            e, acc = carry
            s = jax.lax.sort((-e, orderl, midxl, e), num_keys=3)
            top = s[3][:K]
            # carry-dependent perturbation so nothing hoists
            e2 = e + top.sum() * 1e-9
            return (e2, acc + top[0]), None
        (ef, acc), _ = jax.lax.scan(body, (effl, jnp.float32(0)), None,
                                    length=NBLK)
        return acc
    return jax.vmap(one)(eff, order, midx)


def loop_topk(eff, order, midx):
    def one(effl, orderl, midxl):
        def body(carry, _):
            e, acc = carry
            vals, idx = jax.lax.top_k(e, K)
            e2 = e + vals.sum() * 1e-9
            return (e2, acc + vals[0]), None
        (ef, acc), _ = jax.lax.scan(body, (effl, jnp.float32(0)), None,
                                    length=NBLK)
        return acc
    return jax.vmap(one)(eff, order, midx)


def loop_sort1(eff, order, midx):
    """Single fused int32 key (total-order float bits + idx tiebreak
    infeasible in 32 bits; this times the raw single-key sort cost)."""
    def one(effl, orderl, midxl):
        def body(carry, _):
            e, acc = carry
            s = jax.lax.sort(-e)
            e2 = e + s[:K].sum() * 1e-9
            return (e2, acc + s[0]), None
        (ef, acc), _ = jax.lax.scan(body, (effl, jnp.float32(0)), None,
                                    length=NBLK)
        return acc
    return jax.vmap(one)(eff, order, midx)


print(f"backend={jax.default_backend()} E={E} BK={B*K} NBLK={NBLK}",
      flush=True)
timeit("3-key lax.sort (1024)", loop_sort3, eff, order, midx)
timeit("top_k (1024->32)", loop_topk, eff, order, midx)
timeit("1-key lax.sort (1024)", loop_sort1, eff, order, midx)


# --- paranoid re-timing: force host materialization per rep ---
def timeit_sync(name, fn, *args):
    f = jax.jit(fn)
    _ = np.asarray(f(*args))
    ts = []
    for _ in range(5):
        t0 = time.perf_counter()
        res = np.asarray(f(*args))
        ts.append(time.perf_counter() - t0)
    med = statistics.median(ts)
    print(f"{name:<34} {med*1000:8.2f}ms total  {med/NBLK*1e6:7.1f}us/step"
          f"  (sync)", flush=True)


def rtt_probe(eff, order, midx):
    return eff[:, 0] + 1.0


timeit_sync("tunnel RTT (trivial program)", rtt_probe, eff, order, midx)
timeit_sync("3-key lax.sort (1024)", loop_sort3, eff, order, midx)
timeit_sync("top_k (1024->32)", loop_topk, eff, order, midx)
