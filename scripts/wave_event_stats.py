"""Event-spacing analysis for the block-merge wavefront idea: replays
the classic per-placement wave kernel semantics for ONE headline lane in
numpy and counts 'events' (winner saturation -> refill, skip-set growth,
penalty steps). Average placements-per-event bounds the speedup of a
block kernel that commits all placements between events in one chain
step."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

import bench

h, job, nodes = bench.build_world()
from nomad_tpu import mock
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.reconcile import AllocPlaceResult
from nomad_tpu.solver.service import TpuPlacementService
from nomad_tpu.structs import Plan
from nomad_tpu.solver.binpack import (MAX_SKIP, SKIP_THRESHOLD,
                                      wavefront_compact_host, _wave_p_bucket)

snap = h.state.snapshot()
j = mock.job(id="evstat")
P = int(sys.argv[1]) if len(sys.argv) > 1 else 2000
j.task_groups[0].count = P
tg = j.task_groups[0]
plan = Plan(eval_id="evstat-eval-0000000000000001", priority=50, job=j)
ctx = EvalContext(snap, plan)
places = [AllocPlaceResult(name=f"{j.id}.{tg.name}[{k}]", task_group=tg)
          for k in range(P)]
svc = TpuPlacementService(ctx, j, batch_mode=False, spread_alg=False)
lane = svc.pack(tg, places, nodes)
B = lane.wavefront_B()
compact, scal_f, scal_i, pen, sp = wavefront_compact_host(
    lane.const, lane.init, lane.batch, lane.dtype_name,
    p_pad=_wave_p_bucket(P), B=B)
ask_cpu, ask_mem, count = [float(x) for x in scal_f]
L, n_active = [int(x) for x in scal_i]
C = compact.shape[0]
print(f"B={B} L={L} n_active={n_active} C={C} "
      f"ask_cpu={ask_cpu} ask_mem={ask_mem}")
print(f"capacity col stats: c>0 rows={int((compact[:,0]>0).sum())} "
      f"min={compact[compact[:,0]>0,0].min():.0f} "
      f"median={np.median(compact[compact[:,0]>0,0]):.0f} "
      f"max={compact[:,0].max():.0f}")

# numpy replay of the per-step kernel, tracking events
slot = compact[:B].copy()
jv = np.zeros(B, dtype=np.int64)
cursor = B
events = 0
sat_events = 0
skip_prev = None
run_winner, runs = None, []
t0 = time.time()
for i in range(n_active):
    cs = slot[:, 0]
    fit = jv < cs
    jp1 = (jv + 1).astype(np.float32)
    free_cpu = 1.0 - (slot[:, 1] + jp1 * ask_cpu) / np.maximum(slot[:, 3], 1e-9)
    free_mem = 1.0 - (slot[:, 2] + jp1 * ask_mem) / np.maximum(slot[:, 4], 1e-9)
    binpack = 18.0 - np.exp2(-10.0 * free_cpu) - np.exp2(-10.0 * free_mem)
    coll = slot[:, 5] + jv
    anti = np.where(coll > 0, -(coll + 1.0) / max(count, 1.0), 0.0)
    nsc = 1.0 + (coll > 0) + (slot[:, 6] != 0.0)
    final = (binpack + anti + slot[:, 6]) / nsc
    low = fit & (final <= SKIP_THRESHOLD)
    skip_rank = np.cumsum(low)
    skipped = low & (skip_rank <= MAX_SKIP)
    if skip_prev is not None and not np.array_equal(skipped, skip_prev):
        events += 1
    skip_prev = skipped.copy()
    counted = fit & ~skipped
    cpos = np.cumsum(counted)
    window = counted & (cpos <= L)
    srank = np.cumsum(skipped)
    deficit = max(0, L - min(int(cpos[-1]), L))
    fallback = skipped & (srank <= deficit)
    yielded = window | fallback
    if not yielded.any():
        break
    order = np.where(window, cpos, L + srank)
    eff = np.where(yielded, final, -np.inf)
    best = eff.max()
    is_best = yielded & (eff == best)
    border = order[is_best].min()
    w = int(np.argmax(is_best & (order == border)))
    if run_winner != w:
        runs.append(1)
        run_winner = w
    else:
        runs[-1] += 1
    jv[w] += 1
    if jv[w] >= cs[w]:
        sat_events += 1
        skip_prev = None
        # shift/refill
        entry = compact[min(cursor, C - 1)]
        jv = np.concatenate([jv[:w], jv[w + 1:], [0]])
        slot = np.concatenate([slot[:w], slot[w + 1:], entry[None]], axis=0)
        cursor += 1
print(f"replay {time.time()-t0:.1f}s: placed={i+1} sat_events={sat_events} "
      f"skipset_changes={events}")
runs = np.array(runs)
print(f"winner runs: n={len(runs)} mean={runs.mean():.2f} "
      f"median={np.median(runs):.0f} max={runs.max()}")
total_events = sat_events + events
print(f"placements per (sat+skip) event: "
      f"{(i+1)/max(total_events,1):.1f}")
