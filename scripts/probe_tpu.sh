#!/bin/bash
# Quick TPU reachability probe (subprocess + hard timeout; a wedged axon
# tunnel HANGS jax init rather than failing). Exit 0 = chip reachable.
timeout "${1:-90}" python -u -c "
import os
os.environ.pop('JAX_PLATFORMS', None)
import jax
devs = jax.devices()
assert devs and devs[0].platform != 'cpu', devs
print('TPU OK:', devs)
"
