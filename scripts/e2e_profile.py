"""Sampled-stack profile of the headline-shape e2e round (the r5 pass-3
methodology): run bench.time_batched_path under a 200Hz all-thread
sampler, aggregate leaf frames and (module, function) self-time, print
the top entries. CPU-host control-plane profile; the solver dispatch
itself is timed separately by bench."""
import collections
import os
import sys
import threading
import time

os.environ.pop("JAX_PLATFORMS", None)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax

if os.environ.get("E2E_PROFILE_TPU", "") != "1":
    jax.config.update("jax_platforms", "cpu")

import bench

samples = collections.Counter()
leaf_samples = collections.Counter()
stop = threading.Event()


def sampler():
    me = threading.get_ident()
    while not stop.is_set():
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue
            f = frame
            leaf = f"{os.path.basename(f.f_code.co_filename)}:" \
                   f"{f.f_code.co_name}"
            leaf_samples[leaf] += 1
            seen = set()
            while f is not None:
                key = (os.path.basename(f.f_code.co_filename),
                       f.f_code.co_name)
                if key not in seen:
                    seen.add(key)
                    samples[key] += 1
                f = f.f_back
        time.sleep(0.005)


E = int(sys.argv[1]) if len(sys.argv) > 1 else 32
P = int(sys.argv[2]) if len(sys.argv) > 2 else 2000

t = threading.Thread(target=sampler, daemon=True)
t.start()
t0 = time.perf_counter()
dt, evals, placed = bench.time_batched_path(bench.N_NODES, E, P)
stop.set()
t.join(timeout=2)
total = time.perf_counter() - t0
print(f"\nround: {evals} evals x {P} -> {placed} placed in {dt:.2f}s "
      f"({placed/max(dt,1e-9):.0f}/s); wall incl. warm {total:.1f}s")
n = sum(leaf_samples.values())
print(f"\n== top leaf frames ({n} samples) ==")
for k, v in leaf_samples.most_common(25):
    print(f"{v*100.0/max(n,1):5.1f}%  {k}")
print("\n== top on-stack (module,fn) ==")
for (m, fn), v in samples.most_common(25):
    print(f"{v*100.0/max(n,1):5.1f}%  {m}:{fn}")
