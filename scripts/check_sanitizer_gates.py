#!/usr/bin/env python3
"""check_sanitizer_gates: the five tier-1 sanitizer fixtures cover the
suites they claim (ISSUE 11 satellite; ISSUE 12 added the fourth,
ISSUE 15 the fifth).

The conftest sanitizer fixtures (``_lockcheck_sanitizer``,
``_jitcheck_sanitizer``, ``_statecheck_sanitizer``,
``_schedcheck_explorer``, ``_shardcheck_sanitizer``) gate whole
suites: a suite silently dropping
out of its ``_*_SUITES`` set -- a rename, a typo, a merge accident --
removes the gate without failing anything.  This script asserts:

  * each of the five ``_*_SUITES`` assignments exists in
    tests/conftest.py and is a set of string literals;
  * every suite a set names exists as ``tests/<name>.py`` (a claimed
    gate over a deleted/renamed module covers nothing);
  * each set's matching autouse fixture function exists and reads its
    set;
  * the coverage inventory matches EXPECTED below -- growing or
    shrinking a sanitizer's coverage is a reviewed change, not a
    drive-by (update both, like faultinject.POINTS).

Exit 0 = all gates in place; nonzero lists the drift.  Tier-1 gated by
tests/test_sanitizer_gates.py.
"""
from __future__ import annotations

import argparse
import ast
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the pinned inventory: sanitizer set name -> (fixture name, suites)
EXPECTED = {
    "_LOCKCHECK_SUITES": ("_lockcheck_sanitizer", {
        "test_chaos", "test_dispatch_pipeline", "test_plan_batch",
        "test_churn_storm",
    }),
    "_JITCHECK_SUITES": ("_jitcheck_sanitizer", {
        "test_dispatch_pipeline", "test_lpq", "test_solver_parity",
        "test_mesh_grid",
    }),
    "_STATECHECK_SUITES": ("_statecheck_sanitizer", {
        "test_plan_batch", "test_pack_delta", "test_churn_storm",
        "test_lpq", "test_worker_pool",
    }),
    "_SCHEDCHECK_SUITES": ("_schedcheck_explorer", {
        "test_batch_worker", "test_plan_batch", "test_churn_storm",
        "test_worker_pool",
    }),
    "_SHARDCHECK_SUITES": ("_shardcheck_sanitizer", {
        "test_multichip_dryrun", "test_dispatch_pipeline",
        "test_mesh_grid",
    }),
}


def _parse_conftest(path: str):
    """(sets, fixtures, errors): the ``_*_SUITES`` set literals, the
    function names defined, and any structural problems."""
    errors = []
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError) as e:
        return {}, {}, [f"cannot parse {path}: {e}"]
    sets = {}
    fixtures = {}
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and \
                        t.id.endswith("_SUITES"):
                    if not isinstance(node.value, (ast.Set, ast.Tuple,
                                                   ast.List)):
                        errors.append(
                            f"{t.id} is not a set/tuple/list literal")
                        continue
                    vals = set()
                    for e in node.value.elts:
                        if isinstance(e, ast.Constant) and \
                                isinstance(e.value, str):
                            vals.add(e.value)
                        else:
                            errors.append(
                                f"{t.id} holds a non-literal element")
                    sets[t.id] = vals
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            reads = {n.id for n in ast.walk(node)
                     if isinstance(n, ast.Name)
                     and isinstance(n.ctx, ast.Load)}
            fixtures[node.name] = reads
    return sets, fixtures, errors


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="check_sanitizer_gates",
        description="assert the conftest sanitizer fixtures cover the "
        "suites they claim")
    p.add_argument("--conftest",
                   default=os.path.join(ROOT, "tests", "conftest.py"),
                   help="conftest path (fixture tests point this at a "
                   "synthetic file)")
    p.add_argument("--tests-dir", default=None,
                   help="tests directory (defaults to the conftest's)")
    args = p.parse_args(argv)
    tests_dir = args.tests_dir or os.path.dirname(
        os.path.abspath(args.conftest))

    sets, fixtures, errors = _parse_conftest(args.conftest)

    for set_name, (fixture_name, expected_suites) in EXPECTED.items():
        got = sets.get(set_name)
        if got is None:
            errors.append(f"{set_name} missing from conftest")
            continue
        if got != expected_suites:
            extra = sorted(got - expected_suites)
            missing = sorted(expected_suites - got)
            drift = []
            if extra:
                drift.append(f"unpinned additions {extra}")
            if missing:
                drift.append(f"dropped suites {missing}")
            errors.append(
                f"{set_name} coverage drifted from the pinned "
                f"inventory: {'; '.join(drift)} (update EXPECTED in "
                f"scripts/check_sanitizer_gates.py alongside the "
                f"conftest change)")
        for suite in sorted(got):
            if not os.path.exists(
                    os.path.join(tests_dir, f"{suite}.py")):
                errors.append(
                    f"{set_name} names {suite!r} but "
                    f"tests/{suite}.py does not exist -- the gate "
                    f"covers nothing")
        reads = fixtures.get(fixture_name)
        if reads is None:
            errors.append(f"fixture {fixture_name} missing from "
                          f"conftest")
        elif set_name not in reads:
            errors.append(f"fixture {fixture_name} does not read "
                          f"{set_name} -- the set gates nothing")

    # the complement: a _*_SUITES set in conftest that EXPECTED does
    # not know is an unreviewed fourth gate (or a typo'd rename)
    for set_name in sorted(sets):
        if set_name not in EXPECTED:
            errors.append(f"unexpected suites set {set_name} in "
                          f"conftest (add it to EXPECTED or rename)")

    if errors:
        for e in errors:
            print(f"sanitizer-gates: {e}")
        print(f"sanitizer-gates: {len(errors)} problem(s)")
        return 1
    n = sum(len(s) for s in sets.values())
    print(f"sanitizer gates in place: {len(sets)} fixtures covering "
          f"{n} suite entries")
    return 0


if __name__ == "__main__":
    sys.exit(main())
