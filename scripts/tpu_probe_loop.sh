#!/bin/bash
# Round-long TPU probe loop (VERDICT r4 "next round" item 1).
#
# Probes the chip every ~15 min via scripts/probe_tpu.sh (subprocess +
# hard timeout -- a wedged axon tunnel HANGS jax init rather than
# failing), journals EVERY attempt to TPU_PROBE_JOURNAL.log (committed
# with the round so a wedged tunnel is evidenced, not asserted), and
# fires scripts/capture_tpu_artifacts.sh on the first success.  A
# re-capture is allowed if the last one is >3h old (code moves during
# the round; fresher artifact wins).
cd "$(dirname "$0")/.." || exit 1
JOURNAL=TPU_PROBE_JOURNAL.log
MARKER=/tmp/tpu_capture_done
while true; do
  ts=$(date -u +%FT%TZ)
  if bash scripts/probe_tpu.sh 120 >/tmp/tpu_probe_out.log 2>&1; then
    echo "$ts OK $(grep 'TPU OK' /tmp/tpu_probe_out.log | tail -1)" >>"$JOURNAL"
    if [ ! -f "$MARKER" ] || [ $(($(date +%s) - $(stat -c %Y "$MARKER"))) -gt 10800 ]; then
      echo "$ts CAPTURE starting" >>"$JOURNAL"
      if bash scripts/capture_tpu_artifacts.sh >/tmp/tpu_capture.log 2>&1; then
        touch "$MARKER"
        echo "$ts CAPTURE done (see BENCH_*_headline/tier artifacts)" >>"$JOURNAL"
      else
        echo "$ts CAPTURE FAILED (see /tmp/tpu_capture.log tail):" >>"$JOURNAL"
        tail -3 /tmp/tpu_capture.log >>"$JOURNAL"
      fi
    fi
  else
    echo "$ts FAIL rc=$? (probe timeout -- tunnel wedged or chip absent)" >>"$JOURNAL"
  fi
  sleep 900
done
