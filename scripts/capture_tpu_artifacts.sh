#!/bin/bash
# One-shot TPU artifact capture for the round: headline bench + tier
# shapes. Run when the chip is reachable (check: scripts/probe_tpu.sh or
# /tmp/tpu_probe.log). Each run gates on placement parity.
set -u -o pipefail
cd "$(dirname "$0")/.."
# round tag: explicit $ROUND, else the latest round in PROGRESS.jsonl
# (avoids a per-round hardcoded default silently mislabeling artifacts)
r=${ROUND:-$(python -c "
import json
try:
    line = open('PROGRESS.jsonl').readlines()[-1]
    print('r%02d' % json.loads(line)['round'])
except Exception:
    print('rXX')")}
ts=$(date +%H%M%S)
echo "== default bench =="
python bench.py 2>bench_${ts}.err | tee BENCH_${r}_headline.json || exit 1
for tier in 1 2 3 4 5; do
  echo "== tier $tier =="
  # tier 5's HOST-oracle side (preemption search in python) is ~30min
  # at the full 10K/2000 shape; a recovered-tunnel window is precious,
  # so the preemption tier runs at a reduced-but-honest shape (the
  # parity gate and placements/s metric are shape-normalized).
  # Tiers 1/2 are the BASELINE dev-cluster and batch shapes (5 nodes /
  # 3-TG service; 100 nodes / 1K batch, binpack+spread pair).
  extra=""
  if [ "$tier" = 5 ]; then
    extra="BENCH_NODES=4000 BENCH_PLACEMENTS=800"
  elif [ "$tier" = 2 ]; then
    extra="BENCH_NODES=100 BENCH_PLACEMENTS=1000"
  fi
  env $extra BENCH_TIER=$tier python bench.py 2>tier${tier}_${ts}.err \
    | tee BENCH_${r}_tier${tier}.json || exit 1
done
echo "done; artifacts: BENCH_${r}_headline.json BENCH_${r}_tier{1..5}.json"
