#!/bin/bash
# One-shot TPU artifact capture for the round: headline bench + tier
# shapes. Run when the chip is reachable (check: scripts/probe_tpu.sh or
# /tmp/tpu_probe.log). Each run gates on placement parity.
set -u -o pipefail
cd "$(dirname "$0")/.."
ts=$(date +%H%M%S)
echo "== default bench =="
python bench.py 2>bench_${ts}.err | tee BENCH_local.json || exit 1
for tier in 3 4 5; do
  echo "== tier $tier =="
  BENCH_TIER=$tier python bench.py 2>tier${tier}_${ts}.err \
    | tee BENCH_r03_tier${tier}.json || exit 1
done
echo "done; artifacts: BENCH_local.json BENCH_r03_tier{3,4,5}.json"
