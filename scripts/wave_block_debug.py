"""Tiny-shape debug driver for _solve_wave_block_impl vs the classic
compact kernel: synthetic compact tables, CPU, fast compiles."""
import os
import sys

os.environ.pop("JAX_PLATFORMS", None)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import jax.numpy as jnp

from nomad_tpu.solver.binpack import (
    _solve_wave_block_impl, _solve_wave_compact_impl)

B, K = 8, 4
P = int(sys.argv[1]) if len(sys.argv) > 1 else 12
C = P + B
rng = np.random.default_rng(int(sys.argv[2]) if len(sys.argv) > 2 else 0)

# columns: c, used_cpu, used_mem, cpu_cap, mem_cap, placed, aff, pos
n_fit = int(sys.argv[3]) if len(sys.argv) > 3 else C
compact = np.zeros((C, 8), dtype=np.float32)
compact[:, 7] = -1.0
caps = rng.integers(1, 5, size=n_fit)
cpu_cap = rng.choice([2000.0, 4000.0, 8000.0], size=n_fit)
ask = 500.0
compact[:n_fit, 0] = np.minimum(caps, (cpu_cap // ask))
compact[:n_fit, 1] = rng.integers(0, 2, size=n_fit) * 500.0
compact[:n_fit, 2] = rng.integers(0, 2, size=n_fit) * 256.0
compact[:n_fit, 3] = cpu_cap
compact[:n_fit, 4] = cpu_cap * 2
compact[:n_fit, 5] = rng.integers(0, 3, size=n_fit).astype(np.float32)
compact[:n_fit, 6] = rng.choice([0.0, 0.0, 0.5, -0.25], size=n_fit)
compact[:n_fit, 7] = np.arange(n_fit, dtype=np.float32)
compact[:n_fit, 0] = np.maximum(compact[:n_fit, 0], 1)

scal_f = np.array([ask, 256.0, float(P)], dtype=np.float32)
L = int(sys.argv[4]) if len(sys.argv) > 4 else 5
n_active = P
scal_i = np.array([L, n_active], dtype=np.int32)
pen = np.full(P, -1, dtype=np.int32)

classic = jax.jit(lambda *a: _solve_wave_compact_impl(
    *a, sp=None, spread_alg=False, dtype_name="float32", B=B))
block = jax.jit(lambda *a: _solve_wave_block_impl(
    *a, spread_alg=False, dtype_name="float32", B=B, K=K))

c0 = [np.asarray(x) for x in classic(compact, scal_f, scal_i, pen)]
print("classic done", flush=True)
c1 = [np.asarray(x) for x in block(compact, scal_f, scal_i, pen)]
print("block done", flush=True)
names = ("chosen", "scores", "ny")
ok = True
for nm, a, b in zip(names, c0, c1):
    n = int((a != b).sum())
    if n:
        ok = False
        bad = np.nonzero(a != b)[0][:8]
        print(f"{nm}: {n} mismatches at {bad}")
        print("  classic", a[bad])
        print("  block  ", b[bad])
print("PARITY OK" if ok else "PARITY FAIL")
print("chosen classic", c0[0][:16])
print("chosen block  ", c1[0][:16])
