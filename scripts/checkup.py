#!/usr/bin/env python3
"""checkup: the single-entry static-suite driver (ISSUE 15 satellite).

One command, one exit code for every static gate the repo carries:

  nomadlint        every AST lint rule (scripts/nomadlint.py), with
                   the usual per-site waiver semantics
  knob-doc         scripts/check_knob_doc.py -- every NOMAD_TPU_* env
                   read documented in an OPERATIONS.md knob table
  metrics-doc      scripts/check_metrics_doc.py -- every emitted
                   telemetry series in the metrics reference table
  sanitizer-gates  scripts/check_sanitizer_gates.py -- the conftest
                   sanitizer fixtures cover their pinned suites
  native           build native/ (cmake, else g++), assert the ABI
                   stamp matches nomad_tpu.native.ABI_VERSION, and
                   require a registered numpy-fallback parity test for
                   every exported C kernel (skip-with-notice when no
                   C++ toolchain exists)
  compile-audit    `operator shardcheck --compile-audit` in a fresh
                   subprocess -- AOT-compile every registered mesh
                   program (greedy both spread variants + LPQ) on a
                   virtual 8-device mesh and fail on any audit error
                   or unbudgeted collective (skip-with-notice when
                   jax is unavailable)

``checkup`` runs them all (or a ``--only NAME`` subset, repeatable)
and exits nonzero when ANY component fails -- the one pre-merge gate
a contributor (or CI) needs instead of four separate invocations.
``--sarif PATH`` ('-' = stdout) merges every component's findings into
ONE SARIF 2.1.0 document: nomadlint's kept violations ride verbatim
(file/line regions intact), and each failing legacy component
contributes one result per stdout finding line under its component
name as the rule id.

The standalone scripts keep working unchanged; each stays tier-1
gated by its own test. tests/test_checkup.py gates this driver.
"""
from __future__ import annotations

import argparse
import contextlib
import importlib.util
import io
import json
import os
import sys
from typing import Callable, Dict, List, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPTS = os.path.dirname(os.path.abspath(__file__))


def _load(script: str):
    path = os.path.join(_SCRIPTS, script)
    spec = importlib.util.spec_from_file_location(
        f"_checkup_{script[:-3]}", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _run_nomadlint() -> Tuple[int, List[str], List[dict]]:
    """(rc, finding lines, SARIF results) for the full AST rule set.
    The legacy doc checkers run as their own checkup components, so
    the lint component is rules-only (no double reporting)."""
    nl = _load("nomadlint.py")
    kept, waived = nl.run_ast_rules(ROOT, list(nl.RULE_IDS))
    lines = [repr(v) for v in sorted(kept,
                                     key=lambda v: (v.path, v.line))]
    results = nl.to_sarif(kept, list(nl.RULE_IDS))["runs"][0]["results"]
    rc = 1 if kept else 0
    lines.append(f"({waived} waived)")
    return rc, lines, results


def _run_script(script: str, component: str
                ) -> Tuple[int, List[str], List[dict]]:
    """Run a legacy checker's main() with stdout captured; on failure
    every non-empty output line becomes one SARIF result under the
    component's rule id (the legacy gates report by line, not by
    file/region)."""
    import inspect

    mod = _load(script)
    takes_argv = bool(inspect.signature(mod.main).parameters)
    buf = io.StringIO()
    try:
        with contextlib.redirect_stdout(buf):
            rc = int((mod.main([]) if takes_argv else mod.main()) or 0)
    except SystemExit as e:  # argparse usage errors
        rc = int(e.code or 0)
    lines = [ln for ln in buf.getvalue().splitlines() if ln.strip()]
    results = []
    if rc:
        results = [{
            "ruleId": component,
            "level": "error",
            "message": {"text": ln},
            "locations": [{"physicalLocation": {
                "artifactLocation": {"uri": f"scripts/{script}"},
                "region": {"startLine": 1},
            }}],
        } for ln in lines]
    return rc, lines, results


def _native_results(msgs: List[str]) -> List[dict]:
    return [{
        "ruleId": "native",
        "level": "error",
        "message": {"text": m},
        "locations": [{"physicalLocation": {
            "artifactLocation": {"uri": "native/pack_kernels.cc"},
            "region": {"startLine": 1},
        }}],
    } for m in msgs]


def _run_native() -> Tuple[int, List[str], List[dict]]:
    """The native control-plane gate (ISSUE 17): build native/ (cmake
    when present, else the direct g++ path), assert the built library's
    ABI stamp matches nomad_tpu.native.ABI_VERSION, and fail when any
    exported C kernel lacks a registered numpy-fallback parity test in
    tests/test_native.py::KERNEL_PARITY_TESTS.  With no C++ toolchain
    at all the gate skips with a notice (rc 0) -- the parity-registry
    check still runs, it is pure source inspection."""
    import re
    import shutil
    import subprocess

    if ROOT not in sys.path:
        sys.path.insert(0, ROOT)
    from nomad_tpu import native

    lines: List[str] = []
    failures: List[str] = []

    built = native.available()
    if not built and shutil.which("cmake"):
        try:
            subprocess.run(
                ["cmake", "-S", os.path.join(ROOT, "native"),
                 "-B", os.path.join(ROOT, "native", "build")],
                check=True, capture_output=True, timeout=180)
            subprocess.run(
                ["cmake", "--build",
                 os.path.join(ROOT, "native", "build")],
                check=True, capture_output=True, timeout=180)
            native._load_attempted = False
            native._lib = None
            built = native.available()
        except (subprocess.SubprocessError, OSError) as e:
            failures.append(f"cmake build failed: {e}")
    if not built and not failures:
        if shutil.which("g++"):
            built = native.ensure_built()
            if not built:
                failures.append("g++ build failed (native.ensure_built)")
        elif not shutil.which("cmake"):
            lines.append("notice: no C++ toolchain (cmake/g++) -- "
                         "native build skipped")

    if built:
        got = native._lib.nt_abi_version()
        if got != native.ABI_VERSION:
            failures.append(
                f"ABI mismatch: built lib says {got}, "
                f"nomad_tpu.native.ABI_VERSION is {native.ABI_VERSION} "
                "-- rebuild native/ or fix the version stamp")
        else:
            lines.append(f"built + loaded, ABI v{got}")

    # parity-registry completeness: every exported nt_* symbol must map
    # to an existing test (source inspection -- runs even toolchain-less)
    src = open(os.path.join(ROOT, "native", "pack_kernels.cc"),
               encoding="utf-8").read()
    exported = set(re.findall(
        r"^(?:void|int32_t|int64_t|double)\s+(nt_\w+)\s*\(",
        src, re.MULTILINE))
    tests_src = open(os.path.join(ROOT, "tests", "test_native.py"),
                     encoding="utf-8").read()
    m = re.search(r"KERNEL_PARITY_TESTS\s*=\s*\{(.*?)\n\}",
                  tests_src, re.DOTALL)
    registry = dict(re.findall(r'"(nt_\w+)":\s*\n?\s*"([^"]+)"',
                               m.group(1))) if m else {}
    if not m:
        failures.append("tests/test_native.py has no "
                        "KERNEL_PARITY_TESTS registry")
    for sym in sorted(exported - set(registry)):
        failures.append(f"exported kernel {sym} has no registered "
                        "parity test (KERNEL_PARITY_TESTS)")
    for sym, ref in sorted(registry.items()):
        path, _, test = ref.partition("::")
        full = os.path.join(ROOT, path)
        if not os.path.exists(full) or \
                f"def {test}(" not in open(full, encoding="utf-8").read():
            failures.append(f"{sym}: registered parity test {ref} "
                            "does not exist")

    if failures:
        return 1, lines + failures, _native_results(failures)
    return 0, lines, []


def _run_compile_audit() -> Tuple[int, List[str], List[dict]]:
    """The mesh compile-audit gate (ISSUE 19 satellite): run
    ``operator shardcheck --compile-audit`` in a FRESH subprocess (the
    virtual-device XLA flag only takes effect before jax initializes,
    so the driver process must not compile in-process) and fail on a
    nonzero rc -- audit errors and unbudgeted collectives both exit 1
    there.  With jax not importable the gate skips with a notice
    (rc 0): the static suite stays runnable on doc-only checkouts."""
    import subprocess

    if importlib.util.find_spec("jax") is None:
        return 0, ["notice: jax unavailable -- mesh compile audit "
                   "skipped"], []
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "nomad_tpu.cli", "operator",
           "shardcheck", "--compile-audit"]
    try:
        proc = subprocess.run(cmd, cwd=ROOT, env=env,
                              capture_output=True, text=True,
                              timeout=300)
    except (subprocess.SubprocessError, OSError) as e:
        failures = [f"compile audit subprocess failed: {e}"]
        return 1, failures, [{
            "ruleId": "compile-audit",
            "level": "error",
            "message": {"text": failures[0]},
            "locations": [{"physicalLocation": {
                "artifactLocation": {
                    "uri": "nomad_tpu/shardcheck.py"},
                "region": {"startLine": 1},
            }}],
        }]
    out_lines = [ln for ln in (proc.stdout + proc.stderr).splitlines()
                 if ln.strip()]
    if proc.returncode:
        return 1, out_lines, [{
            "ruleId": "compile-audit",
            "level": "error",
            "message": {"text": ln},
            "locations": [{"physicalLocation": {
                "artifactLocation": {
                    "uri": "nomad_tpu/shardcheck.py"},
                "region": {"startLine": 1},
            }}],
        } for ln in out_lines
            if "error" in ln.lower() or "excess" in ln.lower()
        ] or [{
            "ruleId": "compile-audit",
            "level": "error",
            "message": {"text":
                        f"compile audit exit {proc.returncode}"},
            "locations": [{"physicalLocation": {
                "artifactLocation": {
                    "uri": "nomad_tpu/shardcheck.py"},
                "region": {"startLine": 1},
            }}],
        }]
    n_programs = sum(1 for ln in out_lines
                     if ln.startswith("program:"))
    return 0, [f"{n_programs} mesh program(s) audited clean"], []


COMPONENTS: Dict[str, Callable[[], Tuple[int, List[str], List[dict]]]] = {
    "nomadlint": _run_nomadlint,
    "knob-doc": lambda: _run_script("check_knob_doc.py", "knob-doc"),
    "metrics-doc": lambda: _run_script("check_metrics_doc.py",
                                       "metrics-doc"),
    "sanitizer-gates": lambda: _run_script("check_sanitizer_gates.py",
                                           "sanitizer-gates"),
    "native": _run_native,
    "compile-audit": _run_compile_audit,
}


def to_sarif(results: List[dict], rules: List[str]) -> dict:
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0"
                    ".json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "checkup",
                "informationUri":
                    "https://github.com/nomad-tpu/nomad-tpu",
                "rules": [{"id": r} for r in sorted(set(rules))],
            }},
            "results": results,
        }],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="checkup",
        description="run every static gate (nomadlint + knob-doc + "
        "metrics-doc + sanitizer-gates + native + compile-audit) "
        "with one combined exit code")
    p.add_argument("--only", action="append", default=[],
                   metavar="NAME",
                   help="run only this component (repeatable); "
                   f"known: {', '.join(COMPONENTS)}")
    p.add_argument("--list", action="store_true",
                   help="list component names and exit")
    p.add_argument("--sarif", metavar="PATH", default=None,
                   help="write the merged findings as SARIF 2.1.0 to "
                   "PATH ('-' = stdout)")
    args = p.parse_args(argv)

    if args.list:
        for name in COMPONENTS:
            print(name)
        return 0
    for name in args.only:
        if name not in COMPONENTS:
            print(f"unknown component {name!r} "
                  f"(have: {', '.join(COMPONENTS)})")
            return 2
    selected = args.only or list(COMPONENTS)

    rc = 0
    all_results: List[dict] = []
    rule_ids: List[str] = []
    verdicts = []
    for name in COMPONENTS:
        if name not in selected:
            continue
        crc, lines, results = COMPONENTS[name]()
        verdicts.append((name, crc))
        all_results.extend(results)
        rule_ids.extend(r["ruleId"] for r in results)
        if crc:
            rc = 1
            print(f"== {name}: FAIL (rc={crc})")
            for ln in lines:
                print(f"   {ln}")
        else:
            print(f"== {name}: ok")
    print("checkup: " + "  ".join(
        f"{n}={'FAIL' if c else 'ok'}" for n, c in verdicts)
        + f"  -> exit {rc}")

    if args.sarif:
        doc = to_sarif(all_results, rule_ids or ["checkup"])
        if args.sarif == "-":
            print(json.dumps(doc, indent=2))
        else:
            with open(args.sarif, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=2)
            print(f"checkup: SARIF written to {args.sarif} "
                  f"({len(all_results)} result(s))")
    return rc


if __name__ == "__main__":
    sys.exit(main())
