"""Randomized equivalence fuzz: _solve_wave_block_impl vs the classic
compact kernel over synthetic compact tables (CPU). One process, few
shapes (compile reuse), many seeds."""
import os
import sys

os.environ.pop("JAX_PLATFORMS", None)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import jax.numpy as jnp
from functools import partial

from nomad_tpu.solver.binpack import (
    _solve_wave_block_impl, _solve_wave_compact_impl)

N_SEEDS = int(sys.argv[1]) if len(sys.argv) > 1 else 50
FAILS = 0


def make_case(rng, C, B):
    compact = np.zeros((C, 8), dtype=np.float32)
    compact[:, 7] = -1.0
    n_fit = rng.integers(0, C + 1)
    if n_fit:
        caps = rng.integers(1, 9, size=n_fit).astype(np.float32)
        cpu_cap = rng.choice([2000.0, 4000.0, 8000.0], size=n_fit)
        ask = float(rng.choice([250.0, 500.0, 1000.0]))
        c = np.minimum(caps, np.maximum(cpu_cap // ask, 1.0))
        compact[:n_fit, 0] = c
        compact[:n_fit, 1] = rng.integers(0, 3, size=n_fit) * ask
        compact[:n_fit, 2] = rng.integers(0, 3, size=n_fit) * 128.0
        compact[:n_fit, 3] = cpu_cap
        compact[:n_fit, 4] = cpu_cap * 2
        compact[:n_fit, 5] = rng.choice(
            [0.0, 0.0, 0.0, 1.0, 2.0, 50.0], size=n_fit)
        compact[:n_fit, 6] = rng.choice(
            [0.0, 0.0, 0.5, -0.25, 1.0, -1.0], size=n_fit)
        compact[:n_fit, 7] = rng.permutation(C)[:n_fit].astype(np.float32)
    else:
        ask = 500.0
    # occasionally crush scores negative via huge prior collisions and a
    # tiny count so the skip/fallback machinery engages
    count = float(rng.choice([1.0, 4.0, 30.0, 2000.0]))
    scal_f = np.array([ask, 128.0, count], dtype=np.float32)
    return compact, scal_f


for (C_P, B, K, L) in ((40, 8, 4, 5), (160, 32, 32, 14),
                       (96, 32, 8, 3), (360, 128, 32, 100)):
    P = C_P - B
    classic = jax.jit(partial(_solve_wave_compact_impl, sp=None,
                              spread_alg=False, dtype_name="float32",
                              B=B))
    block = jax.jit(partial(_solve_wave_block_impl, spread_alg=False,
                            dtype_name="float32", B=B, K=K))
    shape_fail = 0
    for seed in range(N_SEEDS):
        rng = np.random.default_rng(seed * 7919 + C_P)
        compact, scal_f = make_case(rng, C_P, B)
        n_active = int(rng.integers(1, P + 1))
        scal_i = np.array([L, n_active], dtype=np.int32)
        pen = np.full(P, -1, dtype=np.int32)
        c0 = [np.asarray(x) for x in classic(compact, scal_f, scal_i, pen)]
        c1 = [np.asarray(x) for x in block(compact, scal_f, scal_i, pen)]
        bad = [int((a != b).sum()) for a, b in zip(c0, c1)]
        if any(bad):
            FAILS += 1
            shape_fail += 1
            if shape_fail <= 2:
                print(f"FAIL shape=(P={P},B={B},K={K},L={L}) seed={seed} "
                      f"n_active={n_active} mism={bad}")
                names = ("chosen", "scores", "ny")
                for nm, a, b in zip(names, c0, c1):
                    idx = np.nonzero(a != b)[0][:6]
                    if len(idx):
                        print(f"  {nm} idx={idx}\n    classic={a[idx]}"
                              f"\n    block  ={b[idx]}")
    print(f"shape (P={P},B={B},K={K},L={L}): "
          f"{N_SEEDS - shape_fail}/{N_SEEDS} seeds exact", flush=True)
print("TOTAL FAILS:", FAILS)
sys.exit(1 if FAILS else 0)
