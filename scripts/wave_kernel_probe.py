"""On-chip wave-kernel perf probe: times the fused compute-only program
(the BENCH headline's `fused_compute_placements_per_sec`) across kernel
variants (scan unroll factor, refill-gather strategy) at the headline
shape. Run only when the chip is reachable; prints one line per variant.

Usage: python scripts/wave_kernel_probe.py [E] [P] [variants...]
  variants are "unroll:gather" pairs, e.g. 8:onehot 16:dynslice
"""
import functools
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

E = int(sys.argv[1]) if len(sys.argv) > 1 else 32
P = int(sys.argv[2]) if len(sys.argv) > 2 else 2000
variants = sys.argv[3:] or ["8:onehot", "16:onehot", "32:onehot",
                            "8:dynslice", "16:dynslice"]

import bench  # noqa: E402  (repo root on path)

t0 = time.time()
h, job, nodes = bench.build_world()
print(f"world built in {time.time()-t0:.1f}s", flush=True)

# build E lanes exactly as time_fused_solver does
from nomad_tpu import mock  # noqa: E402
from nomad_tpu.scheduler.context import EvalContext  # noqa: E402
from nomad_tpu.scheduler.reconcile import AllocPlaceResult  # noqa: E402
from nomad_tpu.solver.service import TpuPlacementService  # noqa: E402
from nomad_tpu.structs import Plan  # noqa: E402

snap = h.state.snapshot()
lanes = []
for i in range(E):
    j = mock.job(id=f"probe-{i}")
    j.task_groups[0].count = P
    tg = j.task_groups[0]
    plan = Plan(eval_id=f"probe-eval-{i:016d}", priority=50, job=j)
    ctx = EvalContext(snap, plan)
    places = [AllocPlaceResult(name=f"{j.id}.{tg.name}[{k}]", task_group=tg)
              for k in range(P)]
    svc = TpuPlacementService(ctx, j, batch_mode=False, spread_alg=False)
    lanes.append(svc.pack(tg, places, nodes))
print(f"{E} lanes packed in {time.time()-t0:.1f}s", flush=True)

import jax  # noqa: E402
import numpy as np  # noqa: E402

print(f"backend: {jax.default_backend()}", flush=True)

baseline_out = None
for v in variants:
    unroll, gather = v.split(":")
    os.environ["NOMAD_TPU_WAVE_UNROLL"] = unroll
    os.environ["NOMAD_TPU_WAVE_GATHER"] = gather
    # fresh trace every variant: the env reads happen at trace time
    from nomad_tpu.solver.binpack import (  # noqa: E402
        _solve_wave_compact_impl, _wave_p_bucket, wavefront_compact_host)
    B = lanes[0].wavefront_B()
    p_pad = _wave_p_bucket(max(l.batch.ask_cpu.shape[0] for l in lanes))
    packs = [wavefront_compact_host(l.const, l.init, l.batch, l.dtype_name,
                                    p_pad=p_pad, B=B) for l in lanes]
    compact = np.stack([p[0] for p in packs])
    scal_f = np.stack([p[1] for p in packs])
    scal_i = np.stack([p[2] for p in packs])
    pen = np.stack([p[3] for p in packs])
    inner = jax.vmap(functools.partial(
        _solve_wave_compact_impl, sp=None, B=B,
        spread_alg=lanes[0].spread_alg, dtype_name=lanes[0].dtype_name))
    fn = jax.jit(inner)
    dev = jax.device_put((compact, scal_f, scal_i, pen))
    tc = time.time()
    out = fn(*dev)
    out[0].block_until_ready()
    compile_s = time.time() - tc
    times = []
    for _ in range(5):
        t1 = time.perf_counter()
        out = fn(*dev)
        out[0].block_until_ready()
        times.append(time.perf_counter() - t1)
    med = statistics.median(times)
    chosen = np.asarray(out[0])
    if baseline_out is None:
        baseline_out = chosen
        par = "ref"
    else:
        par = f"mismatch={int((chosen != baseline_out).sum())}"
    print(f"variant unroll={unroll:>2} gather={gather:<8} "
          f"median {med*1000:7.2f}ms  {E*P/med:10.0f} placements/s  "
          f"compile {compile_s:5.1f}s  {par}", flush=True)
