#!/usr/bin/env python3
"""CI gate: every telemetry series the code emits must appear in the
docs/OPERATIONS.md "Metrics reference" table.

Scans nomad_tpu/ + bench.py for ``metrics.incr/sample/sample_ms/measure``
call sites (any local alias -- the codebase uses both ``metrics`` and
``_tm``), extracts the literal series names (f-string placeholders
normalize to ``<...>`` wildcards, ternaries contribute both arms), and
fails listing any name missing from the doc table. Undocumented drift
is exactly how the `batch_lanes`-rendered-as-ms bug survived two
rounds: nobody could diff "what we emit" against "what we documented".

Exit 0: documented. Exit 1: drift (missing names listed on stdout).
Stale doc entries (documented but never emitted) print as warnings
only -- a satellite removing a series should not be blocked by the doc
it is about to fix, but the noise is visible.
"""
from __future__ import annotations

import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(ROOT, "docs", "OPERATIONS.md")

# a metrics emit call (any receiver alias; _count is tracing.py's
# guarded incr wrapper), then every "nomad.*" string literal within the
# call's argument window
_CALL = re.compile(
    r"\b\w+\.(?:incr|sample_ms|sample|measure|_count)\(", re.MULTILINE)
_NAME = re.compile(r'f?"(nomad\.[A-Za-z0-9_.{}]+)"')


def _normalize(name: str) -> str:
    """f-string placeholders and doc-side <...> both become '*'."""
    name = re.sub(r"\{[^}]*\}", "*", name)
    name = re.sub(r"<[^>]*>", "*", name)
    return name


def emitted_series() -> dict:
    """name -> first 'file:line' emitting it."""
    out: dict = {}
    scan = [os.path.join(ROOT, "bench.py")]
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(ROOT, "nomad_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        scan.extend(os.path.join(dirpath, f) for f in filenames
                    if f.endswith(".py"))
    for path in scan:
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        rel = os.path.relpath(path, ROOT)
        for m in _CALL.finditer(text):
            # argument window: enough for a multi-line ternary, short
            # enough not to swallow the next call's literals
            window = text[m.end():m.end() + 160]
            nxt = _CALL.search(window)
            if nxt:
                window = window[:nxt.start()]
            for nm in _NAME.finditer(window):
                name = _normalize(nm.group(1))
                line = text.count("\n", 0, m.start()) + 1
                out.setdefault(name, f"{rel}:{line}")
    return out


def documented_series() -> set:
    with open(DOC, encoding="utf-8") as f:
        text = f.read()
    marker = "## Metrics reference"
    idx = text.find(marker)
    if idx < 0:
        print(f"ERROR: no '{marker}' section in {DOC}")
        sys.exit(1)
    section = text[idx:]
    nxt = section.find("\n## ", len(marker))
    if nxt > 0:
        section = section[:nxt]
    return {_normalize(m.group(1))
            for m in re.finditer(r"`(nomad\.[A-Za-z0-9_.<>{}]+)`",
                                 section)}


def main() -> int:
    emitted = emitted_series()
    documented = documented_series()
    missing = {n: at for n, at in sorted(emitted.items())
               if n not in documented}
    stale = sorted(documented - set(emitted))
    if stale:
        for n in stale:
            print(f"warning: documented but never emitted: {n}")
    if missing:
        print(f"{len(missing)} emitted series missing from the "
              f"OPERATIONS.md metrics reference table:")
        for n, at in missing.items():
            print(f"  {n}  (emitted at {at})")
        return 1
    print(f"metrics doc in sync: {len(emitted)} emitted series all "
          "documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
