// Native tensorization kernels for nomad-tpu.
//
// The reference's native boundary is go-plugin subprocesses + libcontainer
// (SURVEY.md section 2.4); this framework's equivalent performance-critical
// native component is the host-side marshalling path of the TPU solver:
// folding the live allocation table into dense node-axis usage tensors
// (cpu/mem/disk sums, port bitmaps, dynamic-port counts) and batch plan
// verification. Exposed as a C ABI consumed via ctypes
// (nomad_tpu/native.py), with a pure-numpy fallback.
//
// Build: cmake -S native -B native/build && cmake --build native/build

#include <cstdint>
#include <cmath>
#include <cstring>

extern "C" {

// Fold the alloc table into node-axis usage tensors.
//
// rows: n_rows allocations, SoA layout:
//   node_slot[i]  int32   -- node index, -1 = node unknown (skip)
//   cpu[i]/mem[i]/disk[i] double
//   live[i]       uint8   -- 1 unless client-terminal
//   ports[i*max_ports..]  int32, -1 = empty slot
// node inputs:
//   dyn_lo/dyn_hi int32 per node (dynamic port range)
// outputs (caller-zeroed, length n_pad):
//   used_cpu/used_mem/used_disk double
//   dyn_used int32
//   port_words uint32 (n_pad * 2048) -- caller seeds agent-reserved ports
void nt_pack_usage(const int32_t* node_slot, const double* cpu,
                   const double* mem, const double* disk,
                   const uint8_t* live, const int32_t* ports,
                   int64_t n_rows, int32_t max_ports,
                   const int32_t* dyn_lo, const int32_t* dyn_hi,
                   double* used_cpu, double* used_mem, double* used_disk,
                   int32_t* dyn_used, uint32_t* port_words,
                   int64_t n_pad) {
  const int64_t words_per_node = 2048;
  for (int64_t i = 0; i < n_rows; ++i) {
    if (!live[i]) continue;
    const int32_t slot = node_slot[i];
    if (slot < 0 || slot >= n_pad) continue;
    used_cpu[slot] += cpu[i];
    used_mem[slot] += mem[i];
    used_disk[slot] += disk[i];
    if (port_words == nullptr) continue;  // no port state this eval
    uint32_t* words = port_words + slot * words_per_node;
    const int32_t lo = dyn_lo[slot], hi = dyn_hi[slot];
    for (int32_t p = 0; p < max_ports; ++p) {
      const int32_t port = ports[i * max_ports + p];
      if (port < 0) break;
      if (port >= 65536) continue;
      const uint32_t bit = 1u << (port & 31);
      uint32_t* w = &words[port >> 5];
      if (!(*w & bit)) {
        *w |= bit;
        if (port >= lo && port <= hi) dyn_used[slot] += 1;
      }
    }
  }
}

// Count allocations per node for a specific (job, tg) -- the anti-affinity
// and distinct-hosts inputs. jobtg_hash rows match -> placed; job_hash
// rows match -> placed_job.
void nt_count_placed(const int32_t* node_slot, const uint64_t* job_hash,
                     const uint64_t* jobtg_hash, const uint8_t* live,
                     int64_t n_rows, uint64_t want_job, uint64_t want_jobtg,
                     int32_t* placed, int32_t* placed_job, int64_t n_pad) {
  for (int64_t i = 0; i < n_rows; ++i) {
    if (!live[i]) continue;
    const int32_t slot = node_slot[i];
    if (slot < 0 || slot >= n_pad) continue;
    if (job_hash[i] == want_job) {
      placed_job[slot] += 1;
      if (jobtg_hash[i] == want_jobtg) placed[slot] += 1;
    }
  }
}

// Check whether each of n_check static ports is free on each listed node.
// out[k] = 1 if all ports free on node check_slots[k].
void nt_static_ports_free(const uint32_t* port_words, int64_t n_pad,
                          const int32_t* check_ports, int32_t n_ports,
                          uint8_t* out) {
  const int64_t words_per_node = 2048;
  for (int64_t slot = 0; slot < n_pad; ++slot) {
    const uint32_t* words = port_words + slot * words_per_node;
    uint8_t free = 1;
    for (int32_t p = 0; p < n_ports; ++p) {
      const int32_t port = check_ports[p];
      if (port < 0 || port >= 65536) continue;
      if (words[port >> 5] & (1u << (port & 31))) {
        free = 0;
        break;
      }
    }
    out[slot] = free;
  }
}

// Batch plan verification: node-axis superset check
// (reference: nomad/plan_apply.go:717 evaluateNodePlan -> AllocsFit).
// For each node k: fits iff used + ask <= cap on every dimension.
// Returns the failing dimension per node: 0 ok, 1 cpu, 2 memory, 3 disk.
void nt_verify_fit(const double* cpu_cap, const double* mem_cap,
                   const double* disk_cap, const double* used_cpu,
                   const double* used_mem, const double* used_disk,
                   const double* ask_cpu, const double* ask_mem,
                   const double* ask_disk, int64_t n, int32_t* out_dim) {
  for (int64_t k = 0; k < n; ++k) {
    if (used_cpu[k] + ask_cpu[k] > cpu_cap[k]) out_dim[k] = 1;
    else if (used_mem[k] + ask_mem[k] > mem_cap[k]) out_dim[k] = 2;
    else if (used_disk[k] + ask_disk[k] > disk_cap[k]) out_dim[k] = 3;
    else out_dim[k] = 0;
  }
}

// ---------------------------------------------------------------------------
// Compiled host-baseline oracle: the reference scheduler's per-eval inner
// loop (reference: scheduler/rank.go:205 BinPackIterator.Next,
// scheduler/stack.go:82-95 log2 candidate limit, scheduler/select.go
// LimitIterator/MaxScoreIterator, scheduler/util.go:167 seeded shuffle,
// nomad/structs/funcs.go:236 ScoreFitBinPack) as straight C++ over packed
// node arrays. This is the compiled-host number the TPU solver's
// vs_native_host is measured against: same shuffle, same window semantics,
// same double-precision score math, same tie-breaks as the Python oracle
// (parity-gated in tests/test_native_oracle.py).
//
// Scope: cpu/mem/disk fit + binpack/spread scoring + job anti-affinity +
// eligibility mask. Port/device/core asks route to the host oracle in
// production and are out of the bench workload this baseline times.

static inline uint64_t nt_splitmix64(uint64_t* state, uint64_t* out) {
  *state += 0x9E3779B97F4A7C15ull;
  uint64_t z = *state;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  *out = z ^ (z >> 31);
  return *state;
}

static const double kBinPackMaxFitScore = 18.0;

static void nt_fisher_yates(uint64_t seed, int32_t n, int32_t* order) {
  for (int32_t i = 0; i < n; ++i) order[i] = i;
  uint64_t state = seed;
  for (int32_t i = n - 1; i > 0; --i) {
    uint64_t out;
    nt_splitmix64(&state, &out);
    const int32_t j = static_cast<int32_t>(out % (uint64_t)(i + 1));
    const int32_t tmp = order[i];
    order[i] = order[j];
    order[j] = tmp;
  }
}

// The deterministic per-eval node shuffle (scheduler/util.py
// shuffled_order) as native code -- the Python Fisher-Yates costs ~10ms at
// 10K nodes, a visible slice of the per-eval host budget.
void nt_shuffled_order(uint64_t seed, int32_t n, int32_t* order) {
  nt_fisher_yates(seed, n, order);
}

void nt_solve_eval(int32_t n_nodes, const double* cpu_cap,
                   const double* mem_cap, const double* disk_cap,
                   double* used_cpu, double* used_mem, double* used_disk,
                   int32_t* placed_jobtg, const uint8_t* eligible,
                   uint64_t shuffle_seed, double ask_cpu, double ask_mem,
                   double ask_disk, int32_t desired_count, int32_t limit,
                   int32_t max_skip, double skip_threshold,
                   int32_t n_placements, int32_t spread_alg, int32_t* order,
                   int32_t* out_choice) {
  // Deterministic Fisher-Yates over the base node order, identical to
  // scheduler/util.py shuffle_nodes (splitmix64, j = out % (i+1)).
  nt_fisher_yates(shuffle_seed, n_nodes, order);

  struct Option {
    int32_t node;
    double final_score;
  };
  // LimitIterator defers at most max_skip low-score options; bounded small.
  Option skipped[16];
  if (max_skip > 16) max_skip = 16;

  for (int32_t p = 0; p < n_placements; ++p) {
    int32_t pos = 0;  // source iterator restarts each Select
    int32_t seen = 0, n_skipped = 0, skipped_idx = 0;
    Option best;
    bool have_best = false;

    // source.next(): next shuffled node passing eligibility + fit, scored.
    auto source_next = [&](Option* opt) -> bool {
      while (pos < n_nodes) {
        const int32_t k = order[pos++];
        if (!eligible[k]) continue;
        const double ucpu = used_cpu[k] + ask_cpu;
        const double umem = used_mem[k] + ask_mem;
        const double udisk = used_disk[k] + ask_disk;
        if (ucpu > cpu_cap[k] || umem > mem_cap[k] || udisk > disk_cap[k])
          continue;  // exhausted: BinPackIterator skips, no window slot used
        double score = 0.0;
        if (cpu_cap[k] > 0.0 && mem_cap[k] > 0.0) {
          const double free_cpu = 1.0 - ucpu / cpu_cap[k];
          const double free_ram = 1.0 - umem / mem_cap[k];
          const double total =
              std::pow(10.0, free_cpu) + std::pow(10.0, free_ram);
          score = spread_alg ? (total - 2.0) : (20.0 - total);
          if (score > kBinPackMaxFitScore) score = kBinPackMaxFitScore;
          if (score < 0.0) score = 0.0;
        }
        double final_score = score / kBinPackMaxFitScore;
        const int32_t collisions = placed_jobtg[k];
        if (collisions > 0 && desired_count > 0) {
          const double penalty =
              -1.0 * (double)(collisions + 1) / (double)desired_count;
          final_score = (final_score + penalty) / 2.0;  // mean of 2 scores
        }
        opt->node = k;
        opt->final_score = final_score;
        return true;
      }
      return false;
    };
    // LimitIterator._next_option(): source first, then deferred skips.
    auto next_option = [&](Option* opt) -> bool {
      if (source_next(opt)) return true;
      if (skipped_idx < n_skipped) {
        *opt = skipped[skipped_idx++];
        return true;
      }
      return false;
    };

    // MaxScoreIterator over LimitIterator (select.go semantics, verbatim).
    while (true) {
      if (seen == limit) break;
      Option opt;
      bool have = next_option(&opt);
      if (!have) break;
      if (n_skipped < max_skip) {
        while (have && opt.final_score <= skip_threshold &&
               n_skipped < max_skip) {
          skipped[n_skipped++] = opt;
          have = source_next(&opt);
        }
      }
      seen += 1;
      if (!have) {
        have = next_option(&opt);
        if (!have) break;  // LimitIterator returned None
      }
      if (!have_best || opt.final_score > best.final_score) {
        best = opt;
        have_best = true;
      }
    }

    if (have_best) {
      const int32_t k = best.node;
      used_cpu[k] += ask_cpu;
      used_mem[k] += ask_mem;
      used_disk[k] += ask_disk;
      placed_jobtg[k] += 1;
      out_choice[p] = k;
    } else {
      out_choice[p] = -1;
    }
  }
}

// Whole-group plan verification against the columnar fold state
// (reference: nomad/plan_apply.go evaluateNodePlan over a plan batch).
// One call applies a plan group's deltas to the folded usage and compares
// every touched node, so the applier's verify pre-pass holds the GIL only
// while gathering plan-sized entry arrays, not for the arithmetic.
//
// Inputs:
//   tbl_cpu/tbl_mem/tbl_disk  AllocTable columns (full table)
//   tbl_live_strict           uint8 column; dead rows contribute nothing
//   d_row/d_pos/d_sign        n_delta row-backed deltas: for each entry,
//                             used[dim][d_pos] += d_sign * tbl[dim][d_row]
//                             iff tbl_live_strict[d_row] (stops,
//                             preemptions, in-place replacements,
//                             overlay-removed allocs)
//   a_pos/a_cpu/a_mem/a_disk  n_ask direct value entries; a_into_used[e]
//   a_into_used               routes the entry into used (in-flight
//                             overlay adds) or ask (this plan's
//                             placements)
//   cpu_cap/mem_cap/disk_cap  per-node caps minus node-reserved
//   used_*/ask_*              in/out node-axis accumulators (used_* seeded
//                             from the fold; ask_* caller-zeroed)
// Output: out_dim[k] = 0 ok, 1 cpu, 2 memory, 3 disk.
//
// Entries are applied strictly in order (e then compare), so float
// accumulation order matches the Python oracle's traversal order and the
// numpy fallback's sequential np.add.at -- bitwise-parity-gated.
void nt_verify_plan(const double* tbl_cpu, const double* tbl_mem,
                    const double* tbl_disk, const uint8_t* tbl_live_strict,
                    const int64_t* d_row, const int32_t* d_pos,
                    const int8_t* d_sign, int64_t n_delta,
                    const int32_t* a_pos, const double* a_cpu,
                    const double* a_mem, const double* a_disk,
                    const int8_t* a_into_used, int64_t n_ask,
                    const double* cpu_cap, const double* mem_cap,
                    const double* disk_cap, double* used_cpu,
                    double* used_mem, double* used_disk, double* ask_cpu,
                    double* ask_mem, double* ask_disk, int64_t n,
                    int32_t* out_dim) {
  for (int64_t e = 0; e < n_delta; ++e) {
    const int64_t row = d_row[e];
    if (!tbl_live_strict[row]) continue;
    const int32_t k = d_pos[e];
    const double s = (double)d_sign[e];
    used_cpu[k] += s * tbl_cpu[row];
    used_mem[k] += s * tbl_mem[row];
    used_disk[k] += s * tbl_disk[row];
  }
  for (int64_t e = 0; e < n_ask; ++e) {
    const int32_t k = a_pos[e];
    if (a_into_used[e]) {
      used_cpu[k] += a_cpu[e];
      used_mem[k] += a_mem[e];
      used_disk[k] += a_disk[e];
    } else {
      ask_cpu[k] += a_cpu[e];
      ask_mem[k] += a_mem[e];
      ask_disk[k] += a_disk[e];
    }
  }
  for (int64_t k = 0; k < n; ++k) {
    if (used_cpu[k] + ask_cpu[k] > cpu_cap[k]) out_dim[k] = 1;
    else if (used_mem[k] + ask_mem[k] > mem_cap[k]) out_dim[k] = 2;
    else if (used_disk[k] + ask_disk[k] > disk_cap[k]) out_dim[k] = 3;
    else out_dim[k] = 0;
  }
}

int32_t nt_abi_version() { return 3; }

}  // extern "C"
