// Native tensorization kernels for nomad-tpu.
//
// The reference's native boundary is go-plugin subprocesses + libcontainer
// (SURVEY.md section 2.4); this framework's equivalent performance-critical
// native component is the host-side marshalling path of the TPU solver:
// folding the live allocation table into dense node-axis usage tensors
// (cpu/mem/disk sums, port bitmaps, dynamic-port counts) and batch plan
// verification. Exposed as a C ABI consumed via ctypes
// (nomad_tpu/native.py), with a pure-numpy fallback.
//
// Build: cmake -S native -B native/build && cmake --build native/build

#include <cstdint>
#include <cstring>

extern "C" {

// Fold the alloc table into node-axis usage tensors.
//
// rows: n_rows allocations, SoA layout:
//   node_slot[i]  int32   -- node index, -1 = node unknown (skip)
//   cpu[i]/mem[i]/disk[i] double
//   live[i]       uint8   -- 1 unless client-terminal
//   ports[i*max_ports..]  int32, -1 = empty slot
// node inputs:
//   dyn_lo/dyn_hi int32 per node (dynamic port range)
// outputs (caller-zeroed, length n_pad):
//   used_cpu/used_mem/used_disk double
//   dyn_used int32
//   port_words uint32 (n_pad * 2048) -- caller seeds agent-reserved ports
void nt_pack_usage(const int32_t* node_slot, const double* cpu,
                   const double* mem, const double* disk,
                   const uint8_t* live, const int32_t* ports,
                   int64_t n_rows, int32_t max_ports,
                   const int32_t* dyn_lo, const int32_t* dyn_hi,
                   double* used_cpu, double* used_mem, double* used_disk,
                   int32_t* dyn_used, uint32_t* port_words,
                   int64_t n_pad) {
  const int64_t words_per_node = 2048;
  for (int64_t i = 0; i < n_rows; ++i) {
    if (!live[i]) continue;
    const int32_t slot = node_slot[i];
    if (slot < 0 || slot >= n_pad) continue;
    used_cpu[slot] += cpu[i];
    used_mem[slot] += mem[i];
    used_disk[slot] += disk[i];
    if (port_words == nullptr) continue;  // no port state this eval
    uint32_t* words = port_words + slot * words_per_node;
    const int32_t lo = dyn_lo[slot], hi = dyn_hi[slot];
    for (int32_t p = 0; p < max_ports; ++p) {
      const int32_t port = ports[i * max_ports + p];
      if (port < 0) break;
      if (port >= 65536) continue;
      const uint32_t bit = 1u << (port & 31);
      uint32_t* w = &words[port >> 5];
      if (!(*w & bit)) {
        *w |= bit;
        if (port >= lo && port <= hi) dyn_used[slot] += 1;
      }
    }
  }
}

// Count allocations per node for a specific (job, tg) -- the anti-affinity
// and distinct-hosts inputs. jobtg_hash rows match -> placed; job_hash
// rows match -> placed_job.
void nt_count_placed(const int32_t* node_slot, const uint64_t* job_hash,
                     const uint64_t* jobtg_hash, const uint8_t* live,
                     int64_t n_rows, uint64_t want_job, uint64_t want_jobtg,
                     int32_t* placed, int32_t* placed_job, int64_t n_pad) {
  for (int64_t i = 0; i < n_rows; ++i) {
    if (!live[i]) continue;
    const int32_t slot = node_slot[i];
    if (slot < 0 || slot >= n_pad) continue;
    if (job_hash[i] == want_job) {
      placed_job[slot] += 1;
      if (jobtg_hash[i] == want_jobtg) placed[slot] += 1;
    }
  }
}

// Check whether each of n_check static ports is free on each listed node.
// out[k] = 1 if all ports free on node check_slots[k].
void nt_static_ports_free(const uint32_t* port_words, int64_t n_pad,
                          const int32_t* check_ports, int32_t n_ports,
                          uint8_t* out) {
  const int64_t words_per_node = 2048;
  for (int64_t slot = 0; slot < n_pad; ++slot) {
    const uint32_t* words = port_words + slot * words_per_node;
    uint8_t free = 1;
    for (int32_t p = 0; p < n_ports; ++p) {
      const int32_t port = check_ports[p];
      if (port < 0 || port >= 65536) continue;
      if (words[port >> 5] & (1u << (port & 31))) {
        free = 0;
        break;
      }
    }
    out[slot] = free;
  }
}

// Batch plan verification: node-axis superset check
// (reference: nomad/plan_apply.go:717 evaluateNodePlan -> AllocsFit).
// For each node k: fits iff used + ask <= cap on every dimension.
// Returns the failing dimension per node: 0 ok, 1 cpu, 2 memory, 3 disk.
void nt_verify_fit(const double* cpu_cap, const double* mem_cap,
                   const double* disk_cap, const double* used_cpu,
                   const double* used_mem, const double* used_disk,
                   const double* ask_cpu, const double* ask_mem,
                   const double* ask_disk, int64_t n, int32_t* out_dim) {
  for (int64_t k = 0; k < n; ++k) {
    if (used_cpu[k] + ask_cpu[k] > cpu_cap[k]) out_dim[k] = 1;
    else if (used_mem[k] + ask_mem[k] > mem_cap[k]) out_dim[k] = 2;
    else if (used_disk[k] + ask_disk[k] > disk_cap[k]) out_dim[k] = 3;
    else out_dim[k] = 0;
  }
}

int32_t nt_abi_version() { return 1; }

}  // extern "C"
